"""The device-residency manager: millions of docs on bounded HBM
(INTERNALS §22).

PR 15 made device footprint a first-class measured quantity (exact
dtype x shape per-doc gauges, per-lane aggregates, a peak high-water
mark, exact h2d/d2h byte meters); this tier exploits it to make
bounded-HBM serving a structural invariant instead of an accident of
population size. Three tiers, one ladder:

- **hot**: device-resident in a :class:`~..shard.lane.ShardLane` —
  the only tier that serves commits;
- **warm**: demoted to a host-side AMTPUCKPT1 checkpoint bundle
  (`BundleStore`; the PR-3 codec is the spill format — promotion is
  pure h2d table staging through the existing `export`/`adopt` halves,
  NEVER replay);
- **cold**: warm bundles untouched for ``cold_after`` pager rounds age
  to one spill file each on disk.

Paging is demand-driven by sync traffic: `before_round` runs inside
`ShardedDocSet.deliver_round` BEFORE any lane ingest — stored docs the
round touches page in, brand-new docs reserve estimated bytes, and the
eviction pass makes room FIRST, so the footprint gauge's high-water
mark stays under the budget through the whole round (the reservation
discipline; the cfg18 slo_gate bar is absolute). Admission-aware
prefetch treats a router park as a paging hint: a premature change for
a demoted doc means its dependencies are in flight, so the doc starts
staging before the release needs it. Eviction reads the SAME telemetry
windows the rebalance policy reads, and victim choice is the learned
working-set model of `policy.py` (plain LRU kept as the comparator).

Nothing is ever lost: every doc is, at all times, exactly one of
resident / warm / cold (plus router-parked wire changes for docs in any
tier) — `accounting()` is the exact surface the eviction-under-pressure
test asserts over.
"""

from __future__ import annotations

import functools
import threading
import time

from ..obs import lineage
from .policy import ResidencyConfig, lane_pressure, make_model
from .store import BundleStore


def _locked(fn):
    """Serialize a tier-transition method on the manager's re-entrant
    lock. The round hooks themselves stay caller-thread-only under
    parallel mesh execution (barrier-ordered by `ShardedDocSet`), but
    the reservation-ledger banking inside `page_in`/`_make_room` must
    be atomic against ANY concurrent pager entry point (prefetch hints,
    promotion reads, the thundering-herd stress in
    tests/test_parallel_mesh.py) — interleaved make-room/adopt pairs
    could both fit the budget alone and overshoot it together."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    return wrapper


class ResidencyManager:
    """Tiered doc residency over one :class:`~..shard.set.ShardedDocSet`."""

    def __init__(self, mesh, config: ResidencyConfig = None, **kwargs):
        self.mesh = mesh
        self.config = config if config is not None \
            else ResidencyConfig(**kwargs)
        self.telemetry = mesh.telemetry
        self.store = BundleStore(self.config.spill_dir)
        self.model = make_model(self.config.eviction)
        self._round = 0                 # the pager clock
        self._sizes: dict = {}          # doc_id -> measured device bytes
        self._store_round: dict = {}    # doc_id -> round it was demoted
        self._est_bytes = 0             # max per-doc bytes seen
        self._fresh_bytes = None        # measured fresh-doc allocation
        self._reserved = 0              # round-scoped reservation ledger
        self._in_round = False
        #: guards every tier transition + the reservation ledger (see
        #: `_locked`); re-entrant so page_in -> _make_room -> demote nests
        self._lock = threading.RLock()
        self.peak_resident_bytes = 0
        self.stats = {"page_ins": 0, "page_outs": 0, "prefetches": 0,
                      "hints": 0, "hits": 0, "misses": 0, "cold_ages": 0,
                      "cold_loads": 0, "evictions": 0,
                      "budget_overruns": 0, "placement_moves": 0}

    # -- measurement ----------------------------------------------------

    def resident_bytes(self) -> int:
        """Mesh-wide device-resident bytes (dtype x shape host math —
        never a device sync), refreshing the per-doc size ledger."""
        total = 0
        for lane in self.mesh.lanes:
            for doc_id, doc in lane.docs.items():
                nbytes = doc.device_footprint()["device_bytes"]
                self._sizes[doc_id] = nbytes
                if nbytes > self._est_bytes:
                    self._est_bytes = nbytes
                total += nbytes
        if total > self.peak_resident_bytes:
            self.peak_resident_bytes = total
        return total

    def _fresh_doc_bytes(self) -> int:
        """The exact footprint ``ensure_doc`` will allocate for a
        brand-new doc (tables are slot-capacity-bucketed, so this is a
        constant of the mesh's doc kind + capacity) — measured ONCE
        from a throwaway probe doc, never guessed from the resident
        population (restored docs pack tighter than fresh allocations,
        so a population-derived estimate under-reserves)."""
        if self._fresh_bytes is None:
            from ..obs import device_truth
            from ..shard.lane import _DOC_KINDS
            lane = self.mesh.lanes[0]
            pid = "__residency_probe__"
            if lane.doc_kind == "text":
                ops = [{"action": "ins", "obj": pid, "key": "_head",
                        "elem": 1},
                       {"action": "set", "obj": pid, "key": "__p__:1",
                        "value": "x"}]
            else:
                ops = [{"action": "set", "obj": pid, "key": "k",
                        "value": 0}]
            # tables allocate lazily at the first ingest, so the probe
            # applies one op to land in its capacity bucket — with the
            # footprint gauges suspended (a throwaway measurement must
            # not roll the session peak the budget is asserted against)
            prev, device_truth.ENABLED = device_truth.ENABLED, False
            try:
                with lane.device_ctx():
                    probe = _DOC_KINDS[lane.doc_kind](
                        pid, capacity=lane.capacity)
                    probe.apply_changes([{"actor": "__p__", "seq": 1,
                                          "deps": {}, "ops": ops}])
                    self._fresh_bytes = probe.device_footprint()[
                        "device_bytes"]
            finally:
                device_truth.ENABLED = prev
        return self._fresh_bytes

    def _reserve_estimate(self) -> int:
        """Bytes to reserve for a doc not yet materialized/measured:
        the fresh-doc allocation constant (what a new doc actually
        lands at; an all-time grown max would over-evict, a
        current-population max under-reserves when only compact
        restored docs are resident)."""
        return int(self._fresh_doc_bytes() * self.config.reserve_margin)

    # -- the paging gate (deliver_round integration) --------------------

    def stored_clock(self, doc_id: str):
        """A demoted doc's frontier clock read from its stored bundle's
        hash-verified manifest (`bundle.peek` — a cheap host read, no
        array verification, no promotion). None if the doc is not
        stored."""
        data = self.store.peek(doc_id)
        if data is None:
            return None
        from ..checkpoint import bundle as _bundle
        frag = _bundle.peek(data).get("doc") or {}
        return dict(frag.get("clock") or {})

    @_locked
    def before_round(self, deliveries: dict):
        """The demand-paging pass, called by `ShardedDocSet.deliver_round`
        BEFORE any routing/ingest: a stored doc with causally-READY work
        this round pages in (a demand miss, room made first), a stored
        doc whose changes are ALL premature against its stored frontier
        stays stored (the router parks them — `hint_park` decides
        prefetch), unseen docs reserve estimated bytes, and the budget is
        enforced by eviction of docs OUTSIDE the round's working set —
        the reservation discipline that keeps the peak footprint gauge
        under the budget."""
        self._in_round = True
        self._reserved = 0
        protect = [d for d in deliveries if d not in self.mesh._migrating]
        est = self._reserve_estimate()
        need = 0
        # batched stored-membership: the whole round's doc ids go through
        # ONE learned position probe over the store's sorted id table
        # (store.member_mask, the "residency_clock" site); None keeps the
        # exact per-doc `in` probes as the parity comparator
        stored_mask = self.store.member_mask(protect) if protect else None
        for i, doc_id in enumerate(protect):
            if (doc_id in self.store if stored_mask is None
                    else bool(stored_mask[i])):
                # route against the STORED clock: only causally-ready
                # work justifies burning h2d bandwidth now — premature
                # changes will park either way, and the park hint is
                # the admission-aware prefetch path
                ready, _ = self.mesh._split_ready(
                    list(deliveries[doc_id]),
                    self.stored_clock(doc_id) or {})
                if ready:
                    self.stats["misses"] += 1
                    # page_in itself banks the restored doc's re-growth
                    # headroom in the round ledger
                    self.page_in(doc_id, protect=protect,
                                 changes=deliveries[doc_id])
            elif self._doc_lane(doc_id) is not None:
                self.stats["hits"] += 1
                # a compact (restored earlier) resident doc re-grows to
                # its full capacity bucket when this round ingests it
                need += max(0, est - self._sizes.get(doc_id, est))
            else:
                # brand new: ensure_doc will materialize it inside the
                # lane ingest — reserve its estimated footprint now
                need += est
        self._make_room(need, protect)
        # bank the round's materialization/growth claims: every later
        # page-in this round (prefetch at park, release at drain) must
        # make room UNDER these reservations, not fill them — a
        # _make_room call alone is a check, the ledger is the hold
        self._reserved += need

    @_locked
    def after_round(self, deliveries: dict):
        """The bookkeeping half: touch the model for every doc the round
        actually reached, advance the pager clock, and run the aging
        pass (warm -> cold for bundles past ``cold_after``)."""
        self._round += 1
        self._in_round = False
        self._reserved = 0              # claims materialized into sizes
        for doc_id in deliveries:
            if self._doc_lane(doc_id) is not None:
                self.model.note_touch(doc_id, self._round)
        self.resident_bytes()           # refresh sizes + peak watermark
        # re-enforce: table growth (a capacity-bucket jump) or a stale
        # size estimate can leave the round's commit over budget —
        # nothing is protected here, the model's recency scoring is the
        # protection (docs just touched score ~0 and evict last)
        self._make_room(0)
        self._age_pass()

    @_locked
    def tick(self):
        """The pager heartbeat for rounds that arrive from a tick loop
        (SyncService.tick): advances the clock and ages warm bundles
        even when no mesh traffic flows."""
        self._round += 1
        self._in_round = False
        self._reserved = 0
        self._make_room(0)
        self._age_pass()

    def _age_pass(self):
        if self.config.spill_dir is None:
            return
        cutoff = self._round - self.config.cold_after
        for doc_id in self.store.warm_ids():
            if self._store_round.get(doc_id, self._round) <= cutoff:
                if self.store.age(doc_id):
                    self.stats["cold_ages"] += 1
                    self.telemetry.observe_count("res", "cold_ages")

    # -- paging hints ---------------------------------------------------

    def hint_park(self, doc_id: str, changes=None, protect=()):
        """A router park IS a paging hint: a premature change means the
        doc's missing dependencies are in flight, so a demoted doc
        starts staging back now instead of stalling the release.
        ``protect`` names docs the caller still needs resident this
        round (routed-but-not-yet-ingested) — the prefetch's room-making
        must not evict them."""
        self.stats["hints"] += 1
        if self.config.prefetch and doc_id in self.store \
                and doc_id not in self.mesh._migrating:
            self.stats["prefetches"] += 1
            self.telemetry.observe_count("res", "prefetches")
            self.page_in(doc_id, protect=protect, changes=changes,
                         why="prefetch")

    def hint_release(self, doc_id: str, changes=None, protect=()):
        """A quarantine release is the admission-side hint: the doc is
        about to take an ingest, so page it in if it was demoted
        between park and release."""
        self.stats["hints"] += 1
        self.ensure_resident(doc_id, changes=changes, protect=protect)

    def ensure_resident(self, doc_id: str, changes=None, protect=()):
        """Demand paging for any path about to touch the doc's engine
        state (quarantine drain, reads, round-trip promotion)."""
        if doc_id in self.store and doc_id not in self.mesh._migrating:
            self.stats["misses"] += 1
            self.page_in(doc_id, protect=protect, changes=changes)

    # -- tier transitions -----------------------------------------------

    def _doc_lane(self, doc_id: str):
        lane = self.mesh.lane_of(doc_id)
        return lane if doc_id in lane.docs else None

    def _choose_lane(self, doc_id: str):
        """Budget-aware placement for a page-in: the lane with the
        lightest device footprint, tiebroken by the quietest telemetry
        window (the rebalance policy's signal). A move away from the
        current placement is recorded in the table — ownership follows
        the bytes."""
        lanes = self.mesh.lanes
        if len(lanes) == 1:
            return lanes[0]
        pressure = lane_pressure(self.telemetry, lanes)
        best = min(
            range(len(lanes)),
            key=lambda i: (lanes[i].device_footprint()["device_bytes"],
                           pressure[i], i))
        home = self.mesh.placement.shard_of(doc_id)
        if best != home:
            self.mesh.placement.move(doc_id, best)
            self.stats["placement_moves"] += 1
        return lanes[best]

    @_locked
    def page_in(self, doc_id: str, protect=(), changes=None,
                why: str = "demand"):
        """Promote a warm/cold doc back to device residency: make room
        under the budget, then stage the bundle's tables h2d through
        `ShardLane.adopt` (restore_engine — verified bundle, no replay).
        The page-in dwell is measured two ways: the ``res``/``page_in``
        telemetry span (the cfg18 p99 source) and, for sampled changes,
        the paired ``res/page_wait`` -> ``res/page_in`` lineage hops."""
        was_cold = self.store.tier(doc_id) == "cold"
        bundle = self.store.pop(doc_id)
        if bundle is None:
            return None
        self._store_round.pop(doc_id, None)
        if was_cold:
            self.stats["cold_loads"] += 1
            self.telemetry.observe_count("res", "cold_loads")
        need = self._sizes.get(doc_id, self._reserve_estimate())
        self._make_room(need, tuple(protect) + (doc_id,))
        lane = self._choose_lane(doc_id)
        site = f"lane{lane.index}"
        if lineage.ENABLED and changes:
            lineage.hop_delivery(changes, "res/page_wait", site=site,
                                 doc=doc_id)
        t0 = time.perf_counter_ns()
        doc = lane.adopt(doc_id, bundle)
        dur_ns = time.perf_counter_ns() - t0
        if lineage.ENABLED and changes:
            lineage.hop_delivery(changes, "res/page_in", site=site,
                                 doc=doc_id)
        self.telemetry.observe_span("res", "page_in", dur_ns)
        self.telemetry.observe_count("res", "page_ins")
        self.stats["page_ins"] += 1
        actual = doc.device_footprint()["device_bytes"]
        self._sizes[doc_id] = actual
        if self._in_round:
            # the restored tables pack tighter than the room just made
            # — keep the difference held for this doc's re-growth at
            # the ingest that demanded it
            self._reserved += max(0, need - actual)
        self.model.note_touch(doc_id, self._round)
        return lane

    @_locked
    def demote(self, doc_id: str) -> bool:
        """Hot -> warm: capture the doc as its checkpoint bundle at a
        commit boundary and release the device tables (the lane drops
        the doc's footprint gauge). Refuses (False) for docs that are
        migrating or hold causally-unready queued work — the same
        commit-boundary discipline as `ShardedDocSet.migrate`."""
        if doc_id in self.mesh._migrating:
            return False
        lane = self._doc_lane(doc_id)
        if lane is None:
            return False
        doc = lane.docs[doc_id]
        if doc.queue:
            return False
        bundle = lane.export(doc_id)
        self.store.put(doc_id, bundle)
        self._store_round[doc_id] = self._round
        self._sizes.pop(doc_id, None)
        self.stats["page_outs"] += 1
        self.telemetry.observe_count("res", "page_outs")
        return True

    @_locked
    def _make_room(self, need: int, protect=()):
        """Evict (demote) resident docs until ``resident + need`` fits
        the budget, targeting ``headroom * budget`` once eviction
        triggers (hysteresis). Victims: the highest working-set score
        outside the protected set. A population whose protected working
        set alone exceeds the budget is counted as an overrun — the
        budget must hold at least one round's working set."""
        budget = self.config.budget_bytes
        if not budget:
            return
        need += self._reserved          # the round's banked claims hold
        resident = self.resident_bytes()
        if resident + need <= budget:
            return
        target = min(budget - need,
                     int(budget * self.config.headroom) - need)
        protect = set(protect)
        candidates = [d for lane in self.mesh.lanes for d in lane.docs
                      if d not in protect
                      and d not in self.mesh._migrating]
        candidates.sort(key=lambda d: self.model.score(d, self._round),
                        reverse=True)
        for doc_id in candidates:
            if resident <= target:
                break
            nbytes = self._sizes.get(doc_id, 0)
            if self.demote(doc_id):
                self.stats["evictions"] += 1
                self.telemetry.observe_count("res", "evictions")
                resident -= nbytes
        if resident + need > budget:
            self.stats["budget_overruns"] += 1

    # -- reads ----------------------------------------------------------

    def stored_bundle(self, doc_id: str):
        """A demoted doc's checkpoint WITHOUT promoting it: the stored
        bundle IS the canonical capture (byte-identical — produced by
        the same `capture_engine` at demotion)."""
        return self.store.peek(doc_id)

    def tier_of(self, doc_id: str):
        if self._doc_lane(doc_id) is not None:
            return "hot"
        return self.store.tier(doc_id)

    def accounting(self) -> dict:
        """The full population ledger the eviction-under-pressure test
        asserts over: every doc named in exactly one tier, plus
        router-parked wire-change counts per doc (parked changes belong
        to docs of ANY tier — they are router state, not doc state)."""
        hot = sorted(d for lane in self.mesh.lanes for d in lane.docs)
        tiers = self.store.tiers()
        return {"hot": hot, "warm": tiers["warm"], "cold": tiers["cold"],
                "parked": {d: len(q)
                           for d, q in self.mesh._quarantine.items()
                           if len(q)},
                "resident_bytes": sum(self._sizes.get(d, 0) for d in hot),
                "warm_bytes": tiers["warm_bytes"],
                "cold_bytes": tiers["cold_bytes"]}

    def page_in_p99_ms(self) -> float:
        """Telemetry-bound p99 page-in dwell in ms (the cfg18 SLO term)."""
        return round(
            self.telemetry.quantile_ns("res", "page_in", 0.99) / 1e6, 3)

    def hit_rate(self) -> float:
        """Steady-state residency hit rate: the fraction of delivery
        touches that found their doc already device-resident."""
        seen = self.stats["hits"] + self.stats["misses"]
        return round(self.stats["hits"] / seen, 4) if seen else 1.0

    def metrics(self) -> dict:
        acct = self.accounting()
        return {
            "budget_bytes": self.config.budget_bytes,
            "eviction": self.config.eviction,
            "round": self._round,
            "hot_docs": len(acct["hot"]),
            "warm_docs": len(acct["warm"]),
            "cold_docs": len(acct["cold"]),
            "resident_bytes": acct["resident_bytes"],
            "warm_bytes": acct["warm_bytes"],
            "cold_bytes": acct["cold_bytes"],
            "peak_resident_bytes": self.peak_resident_bytes,
            "hit_rate": self.hit_rate(),
            "page_in_p99_ms": self.page_in_p99_ms(),
            **self.stats,
        }

    def families(self, prefix: str = "amtpu_residency") -> list:
        """Prometheus exposition families (SyncService.scrape appends
        these next to the ``amtpu_device_*`` footprint gauges)."""
        m = self.metrics()
        counters = ("page_ins", "page_outs", "prefetches", "hints",
                    "hits", "misses", "cold_ages", "cold_loads",
                    "evictions", "budget_overruns", "placement_moves")
        fams = [
            (f"{prefix}_docs", "gauge",
             "Doc population per residency tier.",
             [({"tier": t}, m[f"{t}_docs"])
              for t in ("hot", "warm", "cold")]),
            (f"{prefix}_bytes", "gauge",
             "Bytes held per residency tier (device tables / host "
             "bundles / disk spill files).",
             [({"tier": "hot"}, m["resident_bytes"]),
              ({"tier": "warm"}, m["warm_bytes"]),
              ({"tier": "cold"}, m["cold_bytes"])]),
            (f"{prefix}_budget_bytes", "gauge",
             "Configured device budget (0 = unbounded).",
             [({}, m["budget_bytes"])]),
            (f"{prefix}_peak_resident_bytes", "gauge",
             "High-water mark of mesh-wide device-resident bytes as "
             "measured by the manager.",
             [({}, m["peak_resident_bytes"])]),
            (f"{prefix}_hit_rate", "gauge",
             "Fraction of delivery touches that found the doc already "
             "device-resident.",
             [({}, m["hit_rate"])]),
            (f"{prefix}_page_in_p99_ms", "gauge",
             "Telemetry-bound p99 page-in dwell (bundle pop + h2d "
             "staging restore).",
             [({}, m["page_in_p99_ms"])]),
            (f"{prefix}_events_total", "counter",
             "Residency tier transitions and paging events.",
             [({"event": k}, m[k]) for k in counters]),
        ]
        return fams

    def describe(self) -> dict:
        """The postmortem block (rides SyncService.describe / the mesh
        snapshot): tier ladder occupancy, budget posture, paging
        counters, dwell bound, and the model's shape."""
        acct = self.accounting()
        return {
            "schema": "amtpu-residency-v1",
            "config": {"budget_bytes": self.config.budget_bytes,
                       "headroom": self.config.headroom,
                       "cold_after": self.config.cold_after,
                       "spill_dir": self.config.spill_dir,
                       "eviction": self.config.eviction,
                       "prefetch": self.config.prefetch},
            "round": self._round,
            "tiers": {"hot": acct["hot"][:64], "warm": acct["warm"][:64],
                      "cold": acct["cold"][:64]},
            "tier_counts": {"hot": len(acct["hot"]),
                            "warm": len(acct["warm"]),
                            "cold": len(acct["cold"])},
            "bytes": {"resident": acct["resident_bytes"],
                      "warm": acct["warm_bytes"],
                      "cold": acct["cold_bytes"],
                      "peak_resident": self.peak_resident_bytes},
            "parked": acct["parked"],
            "hit_rate": self.hit_rate(),
            "page_in_p99_ms": self.page_in_p99_ms(),
            "stats": dict(self.stats),
            "store": dict(self.store.stats),
            "model": self.model.describe(),
        }

"""Frontend: user-visible document state + change/patch plumbing.

Counterpart of /root/reference/frontend/index.js. The frontend holds the
materialized document (immutable view objects) and talks to a backend only via
plain-JSON change requests and patches, so the backend can be the in-process
oracle, a device-resident columnar engine, or a remote process.

Supports both operation modes of the reference:
- immediate backend (``backend=`` option): changes apply synchronously;
- async mode (no backend): requests queue with optimistic local application,
  reconciled on ``apply_patch`` with sequence matching and an OT transform of
  in-flight requests (frontend/index.js:151-212).
"""

from __future__ import annotations

from .._common import ROOT_ID
from .._uuid import uuid as _uuid
from ..obs import lineage
from .apply_patch import (InboundIndex, apply_diffs, clone_root_object,
                          copy_inbound, update_parent_objects)
from .context import Context
from .proxies import ListProxy, MapProxy, root_object_proxy
from .types import Counter, ListDoc, MapDoc, Table, Text

__all__ = [
    "init", "from_", "change", "empty_change", "apply_patch",
    "can_undo", "undo", "can_redo", "redo",
    "get_object_id", "get_object_by_id", "get_actor_id", "set_actor_id",
    "get_conflicts", "get_backend_state", "get_element_ids",
    "Text", "Table", "Counter", "Frontend",
]


def _update_root_object(doc, updated, inbound, state):
    """New immutable root reflecting `updated`, sharing everything else
    (frontend/index.js:17-50)."""
    new_doc = updated.get(ROOT_ID)
    if new_doc is None:
        new_doc = clone_root_object(doc._cache[ROOT_ID])
        updated[ROOT_ID] = new_doc
    new_doc._options = doc._options
    new_doc._cache = updated
    new_doc._inbound = inbound
    new_doc._state = state

    for object_id, obj in doc._cache.items():
        if object_id not in updated:
            updated[object_id] = obj

    if doc._options.get("freeze"):
        for obj in updated.values():
            if hasattr(obj, "_freeze"):
                obj._freeze()
    return new_doc


def _ensure_single_assignment(ops):
    """Keep only the most recent assignment per (obj, key); merge counter incs
    (frontend/index.js:57-78)."""
    assignments: dict = {}
    result = []
    for op in reversed(ops):
        obj, key, action = op.get("obj"), op.get("key"), op["action"]
        if action in ("set", "del", "link", "inc"):
            if obj not in assignments:
                assignments[obj] = {key: op}
                result.append(op)
            elif key not in assignments[obj]:
                assignments[obj][key] = op
                result.append(op)
            elif assignments[obj][key]["action"] == "inc" and action in ("set", "inc"):
                assignments[obj][key]["action"] = action
                assignments[obj][key]["value"] += op["value"]
        else:
            result.append(op)
    result.reverse()
    return result


def _make_change(doc, request_type, context, options):
    """Queue or apply a change request; returns (new_doc, request)
    (frontend/index.js:89-125)."""
    actor = get_actor_id(doc)
    if not actor:
        raise ValueError("Actor ID must be initialized with set_actor_id() "
                         "before making a change")
    state = dict(doc._state)
    state["seq"] += 1
    deps = dict(state["deps"])
    deps.pop(actor, None)

    request = {"requestType": request_type, "actor": actor, "seq": state["seq"],
               "deps": deps}
    if options and options.get("message") is not None:
        request["message"] = options["message"]
    if options and options.get("undoable") is False:
        request["undoable"] = False
    if context is not None:
        request["ops"] = _ensure_single_assignment(context.ops)

    backend = doc._options.get("backend")
    if backend:
        backend_state, patch = backend.apply_local_change(state["backendState"], request)
        state["backendState"] = backend_state
        state["requests"] = []
        if lineage.ENABLED:
            # the origin hop: the change exists as of this local commit.
            # The origin replica is identified by its actor id — the one
            # label every downstream replica can reconstruct from the
            # change itself with zero coordination (INTERNALS §18.1)
            lineage.hop(actor, state["seq"], "origin", site=actor)
        return _apply_patch_to_doc(doc, patch, state, from_backend=True), request

    if context is None:
        context = Context(doc, actor)
    queued = dict(request)
    queued["before"] = doc
    queued["diffs"] = context.diffs
    state["requests"] = state["requests"] + [queued]
    return _update_root_object(doc, context.updated, context.inbound, state), request


def _apply_patch_to_doc(doc, patch, state, from_backend):
    actor = get_actor_id(doc)
    inbound = copy_inbound(doc._inbound)
    updated: dict = {}
    apply_diffs(patch["diffs"], doc._cache, updated, inbound)
    update_parent_objects(doc._cache, updated, inbound)

    if from_backend:
        seq = (patch.get("clock") or {}).get(actor)
        if seq and seq > state["seq"]:
            state["seq"] = seq
        state["deps"] = patch["deps"]
        state["canUndo"] = patch["canUndo"]
        state["canRedo"] = patch["canRedo"]
    return _update_root_object(doc, updated, inbound, state)


def _transform_request(request, patch):
    """Simple OT of an in-flight local request past a remote patch
    (frontend/index.js:188-212 — same documented-incomplete transform; the
    result is transient and replaced by the backend's authoritative patch)."""
    transformed = []
    for local in request["diffs"]:
        local = dict(local)
        drop = False
        for remote in patch["diffs"]:
            if (local["obj"] == remote["obj"] and local["type"] == "list"
                    and local["action"] in ("insert", "set", "remove")):
                if remote["action"] == "insert" and remote["index"] <= local["index"]:
                    local["index"] += 1
                if remote["action"] == "remove" and remote["index"] < local["index"]:
                    local["index"] -= 1
                if remote["action"] == "remove" and remote["index"] == local["index"]:
                    if local["action"] == "set":
                        local["action"] = "insert"
                    if local["action"] == "remove":
                        drop = True
                        break
        if not drop:
            transformed.append(local)
    request["diffs"] = transformed


def init(options=None):
    """Create an empty document (frontend/index.js:217-241).

    `options` may be an actor-id string or a dict with keys `actorId`,
    `deferActorId`, `freeze`, `backend`.
    """
    if isinstance(options, str):
        options = {"actorId": options}
    elif options is None:
        options = {}
    elif not isinstance(options, dict):
        raise TypeError(f"Unsupported value for init() options: {options!r}")
    else:
        options = dict(options)
    if options.get("actorId") is None and not options.get("deferActorId"):
        options["actorId"] = _uuid()

    root = MapDoc(object_id=ROOT_ID)
    state = {"seq": 0, "requests": [], "deps": {}, "canUndo": False, "canRedo": False}
    if options.get("backend"):
        state["backendState"] = options["backend"].init()
    root._options = options
    root._cache = {ROOT_ID: root}
    root._inbound = InboundIndex()
    root._state = state
    root._freeze()
    return root


def from_(initial_state, options=None):
    """New document initialized with `initial_state` (frontend/index.js:246-248)."""
    new_doc, _ = change(init(options), "Initialization",
                        lambda doc: doc.update(initial_state))
    return new_doc


def change(doc, options=None, callback=None):
    """Run `callback` against a mutable view; returns (new_doc, request)
    (frontend/index.js:264-295)."""
    if isinstance(doc, (MapProxy, ListProxy)):
        raise TypeError("Calls to change cannot be nested")
    if not isinstance(doc, MapDoc) or doc._object_id != ROOT_ID:
        raise TypeError("The first argument to change must be the document root")
    if callable(options) and callback is None:
        options, callback = None, options
    if isinstance(options, str):
        options = {"message": options}
    if options is not None and not isinstance(options, dict):
        raise TypeError("Unsupported type of options")

    actor_id = get_actor_id(doc)
    if not actor_id:
        raise ValueError("Actor ID must be initialized with set_actor_id() "
                         "before making a change")
    context = Context(doc, actor_id)
    callback(root_object_proxy(context))

    if not context.updated:
        context.closed = True
        return doc, None
    update_parent_objects(doc._cache, context.updated, context.inbound)
    result = _make_change(doc, "change", context, options)
    context.closed = True
    return result


def empty_change(doc, options=None):
    """A change with no ops — acknowledges received changes via deps
    (frontend/index.js:305-318)."""
    if isinstance(options, str):
        options = {"message": options}
    if options is not None and not isinstance(options, dict):
        raise TypeError("Unsupported type of options")
    actor_id = get_actor_id(doc)
    if not actor_id:
        raise ValueError("Actor ID must be initialized with set_actor_id() "
                         "before making a change")
    return _make_change(doc, "change", Context(doc, actor_id), options)


def apply_patch(doc, patch):
    """Apply a backend patch, reconciling the in-flight request queue
    (frontend/index.js:326-361)."""
    state = dict(doc._state)

    if state["requests"]:
        base_doc = state["requests"][0]["before"]
        if patch.get("actor") == get_actor_id(doc) and patch.get("seq") is not None:
            if state["requests"][0]["seq"] != patch["seq"]:
                raise ValueError(
                    f"Mismatched sequence number: patch {patch['seq']} does not match "
                    f"next request {state['requests'][0]['seq']}")
            state["requests"] = [dict(r) for r in state["requests"][1:]]
        else:
            state["requests"] = [dict(r) for r in state["requests"]]
    else:
        base_doc = doc
        state["requests"] = []

    if doc._options.get("backend"):
        if patch.get("state") is None:
            raise ValueError("When an immediate backend is used, a patch must "
                             "contain the new backend state")
        state["backendState"] = patch["state"]
        state["requests"] = []
        return _apply_patch_to_doc(doc, patch, state, from_backend=True)

    new_doc = _apply_patch_to_doc(base_doc, patch, state, from_backend=True)
    for request in state["requests"]:
        request["before"] = new_doc
        _transform_request(request, patch)
        new_doc = _apply_patch_to_doc(request["before"], request, state, from_backend=False)
    return new_doc


def _is_undo_redo_in_flight(doc) -> bool:
    return any(r["requestType"] in ("undo", "redo") for r in doc._state["requests"])


def can_undo(doc) -> bool:
    return bool(doc._state["canUndo"]) and not _is_undo_redo_in_flight(doc)


def undo(doc, options=None):
    if isinstance(options, str):
        options = {"message": options}
    if options is not None and not isinstance(options, dict):
        raise TypeError("Unsupported type of options")
    if not doc._state["canUndo"]:
        raise ValueError("Cannot undo: there is nothing to be undone")
    if _is_undo_redo_in_flight(doc):
        raise ValueError("Can only have one undo in flight at any one time")
    return _make_change(doc, "undo", None, options)


def can_redo(doc) -> bool:
    return bool(doc._state["canRedo"]) and not _is_undo_redo_in_flight(doc)


def redo(doc, options=None):
    if isinstance(options, str):
        options = {"message": options}
    if options is not None and not isinstance(options, dict):
        raise TypeError("Unsupported type of options")
    if not doc._state["canRedo"]:
        raise ValueError("Cannot redo: there is no prior undo")
    if _is_undo_redo_in_flight(doc):
        raise ValueError("Can only have one redo in flight at any one time")
    return _make_change(doc, "redo", None, options)


def get_object_id(obj):
    return getattr(obj, "_object_id", None)


def get_object_by_id(doc, object_id):
    if isinstance(doc, (MapProxy, ListProxy)):
        return doc._context.instantiate_proxy(object_id)
    return doc._cache.get(object_id)


def get_actor_id(doc):
    return doc._state.get("actorId") or doc._options.get("actorId")


def set_actor_id(doc, actor_id):
    state = dict(doc._state)
    state["actorId"] = actor_id
    return _update_root_object(doc, {}, doc._inbound, state)


def get_conflicts(obj, key):
    """Conflicting concurrently-assigned values at `key`: {actor_id: value}."""
    if isinstance(obj, ListDoc):
        if 0 <= key < len(obj._conflicts):
            return obj._conflicts[key]
        return None
    if isinstance(obj, Text):
        return obj.elems[key].get("conflicts")
    return obj._conflicts.get(key)


def get_backend_state(doc):
    return doc._state.get("backendState")


def get_element_ids(lst):
    if isinstance(lst, Text):
        return [e.get("elemId") for e in lst.elems]
    return list(lst._elem_ids)


class Frontend:
    """Namespace mirroring the reference's Frontend module, for symmetry with
    ``backend.Backend``."""

    init = staticmethod(init)
    from_ = staticmethod(from_)
    change = staticmethod(change)
    emptyChange = staticmethod(empty_change)
    empty_change = staticmethod(empty_change)
    applyPatch = staticmethod(apply_patch)
    apply_patch = staticmethod(apply_patch)
    canUndo = staticmethod(can_undo)
    can_undo = staticmethod(can_undo)
    undo = staticmethod(undo)
    canRedo = staticmethod(can_redo)
    can_redo = staticmethod(can_redo)
    redo = staticmethod(redo)
    getObjectId = staticmethod(get_object_id)
    get_object_id = staticmethod(get_object_id)
    getObjectById = staticmethod(get_object_by_id)
    get_object_by_id = staticmethod(get_object_by_id)
    getActorId = staticmethod(get_actor_id)
    get_actor_id = staticmethod(get_actor_id)
    setActorId = staticmethod(set_actor_id)
    set_actor_id = staticmethod(set_actor_id)
    getConflicts = staticmethod(get_conflicts)
    get_conflicts = staticmethod(get_conflicts)
    getBackendState = staticmethod(get_backend_state)
    get_backend_state = staticmethod(get_backend_state)
    getElementIds = staticmethod(get_element_ids)
    get_element_ids = staticmethod(get_element_ids)
    Text = Text
    Table = Table
    Counter = Counter

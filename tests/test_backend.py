"""Golden change→patch fixtures for the backend.

Ported from the reference's backend unit suite
(/root/reference/test/backend_test.js) — hand-written change JSON in, exact
patch JSON out, no frontend involved. This is the parity oracle format for the
TPU engine as well (SURVEY.md §4).
"""

import pytest

from automerge_tpu._common import ROOT_ID
from automerge_tpu import backend as Backend

ACTOR = "1234-abcd"


def test_assign_key_in_map():
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
    ]}
    s0 = Backend.init()
    s1, patch1 = Backend.apply_changes(s0, [change1])
    assert patch1 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 1}, "deps": {ACTOR: 1},
        "diffs": [{"action": "set", "obj": ROOT_ID, "path": [], "type": "map",
                   "key": "bird", "value": "magpie"}],
    }


def test_increment_key_in_map():
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "counter", "value": 1, "datatype": "counter"},
    ]}
    change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
        {"action": "inc", "obj": ROOT_ID, "key": "counter", "value": 2},
    ]}
    s0 = Backend.init()
    s1, _ = Backend.apply_changes(s0, [change1])
    s2, patch2 = Backend.apply_changes(s1, [change2])
    assert patch2 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
        "diffs": [{"action": "set", "obj": ROOT_ID, "path": [], "type": "map",
                   "key": "counter", "value": 3, "datatype": "counter"}],
    }


def test_conflict_on_same_key():
    change1 = {"actor": "actor1", "seq": 1, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
    ]}
    change2 = {"actor": "actor2", "seq": 1, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "blackbird"},
    ]}
    s0 = Backend.init()
    s1, _ = Backend.apply_changes(s0, [change1])
    s2, patch2 = Backend.apply_changes(s1, [change2])
    assert patch2 == {
        "canUndo": False, "canRedo": False,
        "clock": {"actor1": 1, "actor2": 1}, "deps": {"actor1": 1, "actor2": 1},
        "diffs": [{"action": "set", "obj": ROOT_ID, "path": [], "type": "map",
                   "key": "bird", "value": "blackbird",
                   "conflicts": [{"actor": "actor1", "value": "magpie"}]}],
    }


def test_delete_key_from_map():
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
    ]}
    change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
        {"action": "del", "obj": ROOT_ID, "key": "bird"},
    ]}
    s0 = Backend.init()
    s1, _ = Backend.apply_changes(s0, [change1])
    s2, patch2 = Backend.apply_changes(s1, [change2])
    assert patch2 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
        "diffs": [{"action": "remove", "obj": ROOT_ID, "path": [], "type": "map", "key": "bird"}],
    }


def test_create_nested_maps():
    birds = "birds-obj-uuid"
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "makeMap", "obj": birds},
        {"action": "set", "obj": birds, "key": "wrens", "value": 3},
        {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
    ]}
    s0 = Backend.init()
    s1, patch1 = Backend.apply_changes(s0, [change1])
    assert patch1 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 1}, "deps": {ACTOR: 1},
        "diffs": [
            {"action": "create", "obj": birds, "type": "map"},
            {"action": "set", "obj": birds, "type": "map", "path": None,
             "key": "wrens", "value": 3},
            {"action": "set", "obj": ROOT_ID, "type": "map", "path": [],
             "key": "birds", "value": birds, "link": True},
        ],
    }


def test_assign_keys_in_nested_maps():
    birds = "birds-obj-uuid"
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "makeMap", "obj": birds},
        {"action": "set", "obj": birds, "key": "wrens", "value": 3},
        {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
    ]}
    change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
        {"action": "set", "obj": birds, "key": "sparrows", "value": 15},
    ]}
    s0 = Backend.init()
    s1, _ = Backend.apply_changes(s0, [change1])
    s2, patch2 = Backend.apply_changes(s1, [change2])
    assert patch2 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
        "diffs": [{"action": "set", "obj": birds, "type": "map", "path": ["birds"],
                   "key": "sparrows", "value": 15}],
    }


def test_create_lists():
    birds = "birds-list-uuid"
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "makeList", "obj": birds},
        {"action": "ins", "obj": birds, "key": "_head", "elem": 1},
        {"action": "set", "obj": birds, "key": f"{ACTOR}:1", "value": "chaffinch"},
        {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
    ]}
    s0 = Backend.init()
    s1, patch1 = Backend.apply_changes(s0, [change1])
    assert patch1 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 1}, "deps": {ACTOR: 1},
        "diffs": [
            {"action": "create", "obj": birds, "type": "list"},
            {"action": "insert", "obj": birds, "type": "list", "path": None,
             "index": 0, "value": "chaffinch", "elemId": f"{ACTOR}:1"},
            {"action": "set", "obj": ROOT_ID, "type": "map", "path": [],
             "key": "birds", "value": birds, "link": True},
        ],
    }


def test_apply_updates_inside_lists():
    birds = "birds-list-uuid"
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "makeList", "obj": birds},
        {"action": "ins", "obj": birds, "key": "_head", "elem": 1},
        {"action": "set", "obj": birds, "key": f"{ACTOR}:1", "value": "chaffinch"},
        {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
    ]}
    change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
        {"action": "set", "obj": birds, "key": f"{ACTOR}:1", "value": "greenfinch"},
    ]}
    s0 = Backend.init()
    s1, _ = Backend.apply_changes(s0, [change1])
    s2, patch2 = Backend.apply_changes(s1, [change2])
    assert patch2 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
        "diffs": [{"action": "set", "obj": birds, "type": "list", "path": ["birds"],
                   "index": 0, "value": "greenfinch"}],
    }


def test_delete_list_elements():
    birds = "birds-list-uuid"
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "makeList", "obj": birds},
        {"action": "ins", "obj": birds, "key": "_head", "elem": 1},
        {"action": "set", "obj": birds, "key": f"{ACTOR}:1", "value": "chaffinch"},
        {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
    ]}
    change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
        {"action": "del", "obj": birds, "key": f"{ACTOR}:1"},
    ]}
    s0 = Backend.init()
    s1, _ = Backend.apply_changes(s0, [change1])
    s2, patch2 = Backend.apply_changes(s1, [change2])
    assert patch2 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
        "diffs": [{"action": "remove", "obj": birds, "type": "list", "path": ["birds"], "index": 0}],
    }


def test_insert_and_delete_in_same_change():
    birds = "birds-list-uuid"
    change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "makeList", "obj": birds},
        {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
    ]}
    change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
        {"action": "ins", "obj": birds, "key": "_head", "elem": 1},
        {"action": "del", "obj": birds, "key": f"{ACTOR}:1"},
    ]}
    s0 = Backend.init()
    s1, _ = Backend.apply_changes(s0, [change1])
    s2, patch2 = Backend.apply_changes(s1, [change2])
    assert patch2 == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
        "diffs": [{"action": "maxElem", "obj": birds, "value": 1, "type": "list",
                   "path": ["birds"]}],
    }


def test_timestamp_at_root():
    now = 1_700_000_000_000
    change = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "now", "value": now, "datatype": "timestamp"},
    ]}
    s0 = Backend.init()
    s1, patch = Backend.apply_changes(s0, [change])
    assert patch == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 1}, "deps": {ACTOR: 1},
        "diffs": [{"action": "set", "obj": ROOT_ID, "type": "map", "path": [],
                   "key": "now", "value": now, "datatype": "timestamp"}],
    }


def test_timestamp_in_list():
    now = 1_700_000_000_000
    lst = "list-uuid"
    change = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
        {"action": "makeList", "obj": lst},
        {"action": "ins", "obj": lst, "key": "_head", "elem": 1},
        {"action": "set", "obj": lst, "key": f"{ACTOR}:1", "value": now, "datatype": "timestamp"},
        {"action": "link", "obj": ROOT_ID, "key": "list", "value": lst},
    ]}
    s0 = Backend.init()
    s1, patch = Backend.apply_changes(s0, [change])
    assert patch == {
        "canUndo": False, "canRedo": False, "clock": {ACTOR: 1}, "deps": {ACTOR: 1},
        "diffs": [
            {"action": "create", "obj": lst, "type": "list"},
            {"action": "insert", "obj": lst, "type": "list", "path": None, "index": 0,
             "value": now, "elemId": f"{ACTOR}:1", "datatype": "timestamp"},
            {"action": "set", "obj": ROOT_ID, "type": "map", "path": [],
             "key": "list", "value": lst, "link": True},
        ],
    }


class TestApplyLocalChange:
    def test_apply_change_requests(self):
        change1 = {"requestType": "change", "actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
        ]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_local_change(s0, change1)
        assert patch1 == {
            "actor": ACTOR, "seq": 1, "canUndo": True, "canRedo": False,
            "clock": {ACTOR: 1}, "deps": {ACTOR: 1},
            "diffs": [{"action": "set", "obj": ROOT_ID, "path": [], "type": "map",
                       "key": "bird", "value": "magpie"}],
        }

    def test_throws_on_duplicate_requests(self):
        change1 = {"requestType": "change", "actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
        ]}
        change2 = {"requestType": "change", "actor": ACTOR, "seq": 2, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "jay"},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_local_change(s0, change1)
        s2, _ = Backend.apply_local_change(s1, change2)
        with pytest.raises(ValueError, match="already been applied"):
            Backend.apply_local_change(s2, change1)
        with pytest.raises(ValueError, match="already been applied"):
            Backend.apply_local_change(s2, change2)


class TestGetPatch:
    def test_most_recent_value_for_key(self):
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "blackbird"},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
            "diffs": [{"action": "set", "obj": ROOT_ID, "type": "map",
                       "key": "bird", "value": "blackbird"}],
        }

    def test_conflicting_values_for_key(self):
        change1 = {"actor": "actor1", "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
        ]}
        change2 = {"actor": "actor2", "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "blackbird"},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            "canUndo": False, "canRedo": False,
            "clock": {"actor1": 1, "actor2": 1}, "deps": {"actor1": 1, "actor2": 1},
            "diffs": [{"action": "set", "obj": ROOT_ID, "type": "map", "key": "bird",
                       "value": "blackbird",
                       "conflicts": [{"actor": "actor1", "value": "magpie"}]}],
        }

    def test_increments_for_key_in_map(self):
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "counter", "value": 1, "datatype": "counter"},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
            {"action": "inc", "obj": ROOT_ID, "key": "counter", "value": 2},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
            "diffs": [{"action": "set", "obj": ROOT_ID, "type": "map", "key": "counter",
                       "value": 3, "datatype": "counter"}],
        }

    def test_nested_maps(self):
        birds = "birds-obj-uuid"
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "makeMap", "obj": birds},
            {"action": "set", "obj": birds, "key": "wrens", "value": 3},
            {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
            {"action": "del", "obj": birds, "key": "wrens"},
            {"action": "set", "obj": birds, "key": "sparrows", "value": 15},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
            "diffs": [
                {"action": "create", "obj": birds, "type": "map"},
                {"action": "set", "obj": birds, "type": "map", "key": "sparrows", "value": 15},
                {"action": "set", "obj": ROOT_ID, "type": "map", "key": "birds",
                 "value": birds, "link": True},
            ],
        }

    def test_create_lists(self):
        birds = "birds-list-uuid"
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "makeList", "obj": birds},
            {"action": "ins", "obj": birds, "key": "_head", "elem": 1},
            {"action": "set", "obj": birds, "key": f"{ACTOR}:1", "value": "chaffinch"},
            {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        assert Backend.get_patch(s1) == {
            "canUndo": False, "canRedo": False, "clock": {ACTOR: 1}, "deps": {ACTOR: 1},
            "diffs": [
                {"action": "create", "obj": birds, "type": "list"},
                {"action": "insert", "obj": birds, "type": "list", "index": 0,
                 "value": "chaffinch", "elemId": f"{ACTOR}:1"},
                {"action": "maxElem", "obj": birds, "type": "list", "value": 1},
                {"action": "set", "obj": ROOT_ID, "type": "map", "key": "birds",
                 "value": birds, "link": True},
            ],
        }

    def test_latest_state_of_list(self):
        birds = "birds-list-uuid"
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "makeList", "obj": birds},
            {"action": "ins", "obj": birds, "key": "_head", "elem": 1},
            {"action": "set", "obj": birds, "key": f"{ACTOR}:1", "value": "chaffinch"},
            {"action": "ins", "obj": birds, "key": f"{ACTOR}:1", "elem": 2},
            {"action": "set", "obj": birds, "key": f"{ACTOR}:2", "value": "goldfinch"},
            {"action": "link", "obj": ROOT_ID, "key": "birds", "value": birds},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
            {"action": "del", "obj": birds, "key": f"{ACTOR}:1"},
            {"action": "ins", "obj": birds, "key": f"{ACTOR}:1", "elem": 3},
            {"action": "set", "obj": birds, "key": f"{ACTOR}:3", "value": "greenfinch"},
            {"action": "set", "obj": birds, "key": f"{ACTOR}:2", "value": "goldfinches!!"},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            "canUndo": False, "canRedo": False, "clock": {ACTOR: 2}, "deps": {ACTOR: 2},
            "diffs": [
                {"action": "create", "obj": birds, "type": "list"},
                {"action": "insert", "obj": birds, "type": "list", "index": 0,
                 "value": "greenfinch", "elemId": f"{ACTOR}:3"},
                {"action": "insert", "obj": birds, "type": "list", "index": 1,
                 "value": "goldfinches!!", "elemId": f"{ACTOR}:2"},
                {"action": "maxElem", "obj": birds, "type": "list", "value": 3},
                {"action": "set", "obj": ROOT_ID, "type": "map", "key": "birds",
                 "value": birds, "link": True},
            ],
        }

    def test_nested_maps_in_lists(self):
        todos, item = "todos-uuid", "item-uuid"
        change = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "makeList", "obj": todos},
            {"action": "ins", "obj": todos, "key": "_head", "elem": 1},
            {"action": "makeMap", "obj": item},
            {"action": "set", "obj": item, "key": "title", "value": "water plants"},
            {"action": "set", "obj": item, "key": "done", "value": False},
            {"action": "link", "obj": todos, "key": f"{ACTOR}:1", "value": item},
            {"action": "link", "obj": ROOT_ID, "key": "todos", "value": todos},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change])
        assert Backend.get_patch(s1) == {
            "canUndo": False, "canRedo": False, "clock": {ACTOR: 1}, "deps": {ACTOR: 1},
            "diffs": [
                {"action": "create", "obj": item, "type": "map"},
                {"action": "set", "obj": item, "type": "map", "key": "title", "value": "water plants"},
                {"action": "set", "obj": item, "type": "map", "key": "done", "value": False},
                {"action": "create", "obj": todos, "type": "list"},
                {"action": "insert", "obj": todos, "type": "list", "index": 0,
                 "value": item, "link": True, "elemId": f"{ACTOR}:1"},
                {"action": "maxElem", "obj": todos, "type": "list", "value": 1},
                {"action": "set", "obj": ROOT_ID, "type": "map", "key": "todos",
                 "value": todos, "link": True},
            ],
        }


class TestCausalOrdering:
    def test_queues_changes_until_ready(self):
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "jay"},
        ]}
        s0 = Backend.init()
        # change2 arrives first: buffered, no diffs, missing deps reported
        s1, patch1 = Backend.apply_changes(s0, [change2])
        assert patch1["diffs"] == []
        assert Backend.get_missing_deps(s1) == {ACTOR: 1}
        # change1 arrives: both apply in causal order
        s2, patch2 = Backend.apply_changes(s1, [change1])
        assert Backend.get_missing_deps(s2) == {}
        assert [d["value"] for d in patch2["diffs"]] == ["magpie", "jay"]
        assert patch2["clock"] == {ACTOR: 2}

    def test_duplicate_changes_are_idempotent(self):
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2, patch = Backend.apply_changes(s1, [change1])
        assert patch["diffs"] == []
        assert patch["clock"] == {ACTOR: 1}

    def test_inconsistent_seq_reuse_raises(self):
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
        ]}
        change1b = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "jay"},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        with pytest.raises(RuntimeError, match="Inconsistent reuse"):
            Backend.apply_changes(s1, [change1b])


class TestStateBranching:
    """Old BackendStates stay usable after the lineage moves on (the
    command-log fork replaces Immutable.js persistence)."""

    def test_stale_state_reads(self):
        change1 = {"actor": ACTOR, "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "magpie"},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "bird", "value": "jay"},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2, _ = Backend.apply_changes(s1, [change2])
        # s1 still materializes its own snapshot
        patch1 = Backend.get_patch(s1)
        assert patch1["diffs"][-1]["value"] == "magpie"
        assert patch1["clock"] == {ACTOR: 1}
        # diffing historical states works
        assert len(Backend.get_changes(s1, s2)) == 1
        assert len(Backend.get_changes(s0, s2)) == 2

    def test_stale_state_branching_writes(self):
        change1 = {"actor": "a1", "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "x", "value": 1},
        ]}
        change2a = {"actor": "a1", "seq": 2, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "x", "value": 2},
        ]}
        change2b = {"actor": "a2", "seq": 1, "deps": {}, "ops": [
            {"action": "set", "obj": ROOT_ID, "key": "y", "value": 3},
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2a, _ = Backend.apply_changes(s1, [change2a])   # lineage A
        s2b, patch_b = Backend.apply_changes(s1, [change2b])  # fork from s1
        assert patch_b["clock"] == {"a1": 1, "a2": 1}
        assert s2a.clock == {"a1": 2}
        # both branches materialize correctly
        pa = Backend.get_patch(s2a)
        pb = Backend.get_patch(s2b)
        assert {d["key"]: d["value"] for d in pa["diffs"]} == {"x": 2}
        assert {d["key"]: d["value"] for d in pb["diffs"]} == {"x": 1, "y": 3}


def test_merge_and_get_changes_for_actor():
    c_one = {"actor": "actor1", "seq": 1, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "document", "value": "watch me now"},
    ]}
    c_two1 = {"actor": "actor2", "seq": 1, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "document", "value": "i can mash potato"},
    ]}
    c_two2 = {"actor": "actor2", "seq": 2, "deps": {}, "ops": [
        {"action": "set", "obj": ROOT_ID, "key": "document", "value": "i can do the twist"},
    ]}
    one, _ = Backend.apply_changes(Backend.init(), [c_one])
    two, _ = Backend.apply_changes(Backend.init(), [c_two1, c_two2])
    merged, patch = Backend.merge(one, two)
    assert merged.clock == {"actor1": 1, "actor2": 2}
    actor_changes = Backend.get_changes_for_actor(merged, "actor2")
    assert len(actor_changes) == 2
    assert actor_changes[0]["actor"] == "actor2"

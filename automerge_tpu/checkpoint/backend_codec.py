"""Backend-level checkpoint codec: whole document lineages <-> bundles.

Captures a backend state — the device tier's ``DeviceBackendState`` (the
``_DeviceCore`` object graph: per-object columnar docs, root map, change
history, clock/deps) or the oracle's ``BackendState`` — into one bundle,
and restores it without replaying the op history through the round
protocol.

Restore contract (pinned by tests/test_checkpoint.py):

- The restored document renders byte-identically to ``load(save(doc))``
  and serves ``save``/``get_changes``/sync exactly like it (the full
  change history rides in the bundle as a hashed JSON blob; per-actor
  ``states`` and their allDeps closures are rebuilt with cheap host dict
  work — the transitive-closure walk — never via engine replay).
- Undo/redo history is dropped, matching ``api.load`` semantics.
- The restored core's command log is a single synthetic
  ``("apply", history ++ queue, False)`` entry, so the log-replay
  invariants (failure-atomic restore, stale-state forks, oracle
  graduation) hold unchanged.
- Oracle lineages have no columnar state to snapshot; they checkpoint as
  compact change-log bundles and restore by oracle replay (host-only,
  no device compiles — still far cheaper than a device replay, and the
  uniform fallback tier).
"""

from __future__ import annotations

import json

from .._common import ROOT_ID, transitive_deps
from ..resilience.errors import CheckpointError
from . import bundle as _bundle
from .engine_codec import capture_engine_doc, encode_grab, grab, \
    restore_engine_doc

_ENGINE_DEVICE = "device"
_ENGINE_ORACLE = "oracle"


def _backend_mods():
    from ..backend import device as _device
    from ..backend import facade as _oracle
    return _device, _oracle


def capture_state(state, assume_quiescent: bool = True) -> bytes:
    """Serialize a backend state (device or oracle lineage) to a bundle.

    ``assume_quiescent=True`` (the default) is for callers on the
    document's mutator thread — the sync tier, a quiescent test, the
    ``api.checkpoint`` path — and captures the live core directly. An
    async caller (the checkpoint writer's worker) passes ``False``: the
    capture then runs against a PRIVATE core forked from the state's
    command-log prefix, so a mutation racing the walk can never tear the
    snapshot (the fork replay happens on the worker, off the commit
    path)."""
    manifest, arrays = capture_state_pieces(state, assume_quiescent)
    return _bundle.encode(manifest, arrays)


def capture_state_pieces(state, assume_quiescent: bool = True):
    _device, _oracle = _backend_mods()
    if isinstance(state, _oracle.BackendState):
        manifest = {
            "engine": _ENGINE_ORACLE,
            "clock": dict(state.clock),
            "deps": dict(state.deps),
        }
        arrays = {
            "history_json": _bundle.json_array(state.history()),
            "queue_json": _bundle.json_array(list(state.queue)),
        }
        return manifest, arrays
    if not isinstance(state, _device.DeviceBackendState):
        raise CheckpointError(
            f"cannot checkpoint backend state of type {type(state).__name__}")
    if assume_quiescent and state._is_current():
        core = state._core
        core.flush_pending()   # engine state must be current before capture
    else:
        # a stale view, or a live core owned by another thread: replay the
        # command-log prefix into a private core (deterministic, immutable
        # inputs) and capture that — never a torn read of shared state
        core = state._core.fork(state._version)
        core.flush_pending()
    objects = []
    arrays = {}
    for i, oid in enumerate([ROOT_ID] + list(core.obj_order)):
        wrapper = core.root if oid == ROOT_ID else core.objects[oid]
        prefix = f"obj{i}_"
        frag, obj_arrays = capture_engine_doc(wrapper.doc, prefix)
        frag.pop("all_deps", None)   # rebuilt once from history at restore
        frag["prefix"] = prefix
        frag["wrapper_kind"] = wrapper.kind
        frag["max_elem"] = int(wrapper.max_elem)
        frag["announced"] = bool(getattr(wrapper, "announced", True))
        objects.append(frag)
        arrays.update(obj_arrays)
    manifest = {
        "engine": _ENGINE_DEVICE,
        "clock": dict(core.clock),
        "deps": dict(core.deps),
        "objects": objects,
        "obj_order": list(core.obj_order),
    }
    arrays["history_json"] = _bundle.json_array(core.history)
    arrays["queue_json"] = _bundle.json_array(core.queue)
    return manifest, arrays


def _rebuild_states(history: list) -> dict:
    """Per-actor change lists + allDeps closures from the applied history
    (history is in application order, so every closure input precedes its
    use) — the cheap host-dict half of ``_DeviceCore._admit``."""
    states: dict = {}
    for ch in history:
        try:
            actor, seq = ch["actor"], ch["seq"]
        except (TypeError, KeyError) as exc:
            raise CheckpointError(
                f"malformed change in checkpoint history: {exc}") from None
        base = dict(ch.get("deps", {}))
        base[actor] = seq - 1
        all_deps = transitive_deps(states, base)
        lst = states.setdefault(actor, [])
        if seq != len(lst) + 1:
            raise CheckpointError(
                f"checkpoint history is not in application order: actor "
                f"{actor!r} seq {seq} after {len(lst)} prior changes")
        lst.append({"change": ch, "allDeps": all_deps})
    return states


def restore_state(data: bytes):
    """Rebuild a backend state from a bundle. Raises CheckpointError on
    any integrity or structural failure, before any state escapes."""
    manifest, arrays = _bundle.decode(data)
    engine = manifest.get("engine")
    _device, _oracle = _backend_mods()
    try:
        history = _bundle.json_unarray(arrays["history_json"])
        queue = _bundle.json_unarray(arrays["queue_json"])
    except (KeyError, ValueError) as exc:
        raise CheckpointError(
            f"checkpoint history payload unreadable: {exc}") from None
    if not isinstance(history, list) or not isinstance(queue, list):
        raise CheckpointError("checkpoint history/queue must be arrays")

    if engine == _ENGINE_ORACLE:
        from ..resilience.validation import prevalidated
        state = _oracle.init()
        with prevalidated():
            if history or queue:
                state, _ = _oracle.apply_changes(state, history + queue)
        return state
    if engine != _ENGINE_DEVICE:
        raise CheckpointError(f"unknown checkpoint engine {engine!r}")

    core = _device._DeviceCore()
    core.states = _rebuild_states(history)
    core.history = list(history)
    core.queue = list(queue)
    core.clock = dict(manifest.get("clock", {}))
    core.deps = dict(manifest.get("deps", {}))
    shared_deps = core._seed_all_deps()

    objects = manifest.get("objects")
    obj_order = manifest.get("obj_order")
    if not isinstance(objects, list) or not isinstance(obj_order, list):
        raise CheckpointError("checkpoint manifest is missing its object "
                              "table")
    by_id = {}
    for frag in objects:
        doc = restore_engine_doc(frag, arrays, frag.get("prefix", ""),
                                 shared_all_deps=shared_deps)
        if frag["type"] == "text":
            wrapper = _device._TextObj.__new__(_device._TextObj)
            wrapper.kind = frag.get("wrapper_kind", "text")
            wrapper.doc = doc
            wrapper.max_elem = int(frag.get("max_elem", 0))
            wrapper.prev_n = 0
            wrapper.prev_vis = None
            wrapper.prev_value = None
            wrapper.prev_conf = {}
            wrapper.announced = bool(frag.get("announced", True))
            wrapper.ov = None
            wrapper._pool_scan = (0, False)
            wrapper.snapshot()      # net-diff baseline (host mirrors are
            # already planted by restore_engine_doc — no device fetch)
        else:
            wrapper = _device._MapObj.__new__(_device._MapObj)
            wrapper.kind = frag.get("wrapper_kind", "map")
            wrapper.doc = doc
            wrapper.max_elem = int(frag.get("max_elem", 0))
            wrapper.announced = bool(frag.get("announced", True))
            wrapper.ov = None
            wrapper.prev = wrapper.current()
        by_id[doc.obj_id] = wrapper
    if ROOT_ID not in by_id:
        raise CheckpointError("checkpoint bundle has no root object")
    core.root = by_id[ROOT_ID]
    core.obj_order = list(obj_order)
    try:
        core.objects = {oid: by_id[oid] for oid in obj_order}
    except KeyError as exc:
        raise CheckpointError(
            f"checkpoint object table is missing {exc}") from None
    # one synthetic log entry keeps the core == its-log invariant for
    # stale-state forks, failure-atomic restore, and oracle graduation
    if history or queue:
        core.commands = [("apply", list(history) + list(queue), False)]
    return _device.DeviceBackendState(core, len(core.commands))


def restore_state_or_replay(data: bytes, fallback_changes=None):
    """Restore from a bundle; on CheckpointError, fall back to full log
    replay of ``fallback_changes`` (when provided), else re-raise."""
    try:
        return restore_state(data)
    except CheckpointError:
        if fallback_changes is None:
            raise
        import logging
        logging.getLogger("automerge_tpu.checkpoint").warning(
            "checkpoint bundle failed validation; falling back to full "
            "log replay (%d changes)", len(fallback_changes))
        from ..backend import default as Backend
        state, _ = Backend.apply_changes(Backend.init(), fallback_changes)
        return state


# re-exported for the writer / tests
__all__ = ["capture_state", "capture_state_pieces", "restore_state",
           "restore_state_or_replay", "grab", "encode_grab"]

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# The test suite targets a deterministic 8-device virtual CPU mesh: the
# sharding tests need multiple devices, and unit tests must not depend on
# TPU-tunnel health or remote-compile latency. The axon TPU plugin registers
# itself from sitecustomize at interpreter start and, once registered, jax
# initializes it regardless of JAX_PLATFORMS — so when it is present, the
# whole pytest process re-execs with the plugin disabled (restoring pytest's
# captured fds first). The scrub recipe is shared with the driver's multichip
# dryrun (automerge_tpu/_env.py — jax-free import). Set
# AUTOMERGE_TPU_TESTS_ON_TPU=1 to run on the real chip instead.

from automerge_tpu._env import virtual_cpu_env  # noqa: E402

_env = virtual_cpu_env(8)
if os.environ.get("AUTOMERGE_TPU_TESTS_ON_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = _env["JAX_PLATFORMS"]
    os.environ["XLA_FLAGS"] = _env["XLA_FLAGS"]
for _k in ("JAX_COMPILATION_CACHE_DIR",
           "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"):
    os.environ.setdefault(_k, _env[_k])


def pytest_configure(config):
    if (os.environ.get("PALLAS_AXON_POOL_IPS")
            and os.environ.get("AUTOMERGE_TPU_TESTS_ON_TPU") != "1"):
        capman = config.pluginmanager.getplugin("capturemanager")
        if capman is not None:
            capman.stop_global_capturing()
        os.execve(sys.executable,
                  [sys.executable, "-m", "pytest",
                   *config.invocation_params.args],
                  virtual_cpu_env(8))

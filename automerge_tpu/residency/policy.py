"""Eviction policy for the residency manager: which resident doc leaves
the device when the budget needs room.

Two scorers share one contract — ``score(doc_id, now_round)`` returns a
number where HIGHER means "safer to evict":

- **lru**: score = rounds since last touch (ties broken toward fewer
  lifetime ops). The classic baseline, kept as the comparator.
- **learned** (default): a cheap learned working-set model in the
  RocksDB learned-index spirit (PAPERS.md): instead of one global
  recency order, each doc carries an EWMA of its own inter-touch gap —
  its serving *rhythm* — seeded for cold-start docs by a 2-parameter
  online regression of log(gap) on log(1 + touches) fit across the
  whole population (closed-form normal equations, O(1) per touch, no
  training loop, no dependency). The score is ``age / predicted_gap``:
  a doc touched every 50 rounds and last seen 5 rounds ago scores 0.1
  and survives, while a doc with a 1-round rhythm that went quiet 5
  rounds ago scores 5.0 and leaves — exactly the inversion plain LRU
  gets wrong for mixed-rhythm populations (pinned in
  tests/test_residency.py).

Pressure ordering reads the SAME telemetry windows the rebalance policy
reads (``shard`` / ``lane<i>_admitted_ops``, `shard/rebalance.py`):
`lane_pressure` ranks lanes by recent window load so budget-aware
placement can prefer quiet, empty lanes without new bookkeeping.
"""

from __future__ import annotations

import math


class ResidencyConfig:
    """Residency knobs (bounded-everything, like ServiceConfig)."""

    __slots__ = ("budget_bytes", "headroom", "cold_after", "spill_dir",
                 "eviction", "prefetch", "reserve_margin")

    def __init__(self, budget_bytes: int = 0, headroom: float = 0.85,
                 cold_after: int = 64, spill_dir: str = None,
                 eviction: str = "learned", prefetch: bool = True,
                 reserve_margin: float = 1.0):
        if eviction not in ("learned", "lru"):
            raise ValueError(f"unknown eviction policy {eviction!r}")
        #: device budget in bytes over the WHOLE mesh (0 = unbounded:
        #: the manager still tiers and meters, but never evicts)
        self.budget_bytes = int(budget_bytes)
        #: when a reservation breaches the budget, evict down to
        #: headroom * budget — hysteresis so every round doesn't evict
        self.headroom = float(headroom)
        #: warm bundles untouched for this many pager rounds age to disk
        self.cold_after = int(cold_after)
        self.spill_dir = spill_dir
        self.eviction = eviction
        #: a router park is a paging hint: prefetch the parked doc
        self.prefetch = bool(prefetch)
        #: reservation multiplier for docs whose size is only estimated
        self.reserve_margin = float(reserve_margin)


class WorkingSetModel:
    """Per-doc inter-touch rhythm + global learned cold-start prior."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self._gap: dict = {}        # doc_id -> EWMA inter-touch gap
        self._last: dict = {}       # doc_id -> last touch round
        self._touches: dict = {}    # doc_id -> lifetime touch count
        # online least squares for log(gap) ~ w0 + w1 * log(1+touches):
        # running sums are the whole model state (closed-form solve)
        self._n = 0
        self._sx = self._sy = self._sxx = self._sxy = 0.0

    def note_touch(self, doc_id: str, now_round: int):
        last = self._last.get(doc_id)
        self._last[doc_id] = now_round
        touches = self._touches.get(doc_id, 0) + 1
        self._touches[doc_id] = touches
        if last is None:
            return
        gap = max(1, now_round - last)
        prev = self._gap.get(doc_id)
        self._gap[doc_id] = gap if prev is None else \
            (1 - self.alpha) * prev + self.alpha * gap
        x = math.log1p(touches)
        y = math.log(gap)
        self._n += 1
        self._sx += x
        self._sy += y
        self._sxx += x * x
        self._sxy += x * y

    def _prior_gap(self, doc_id: str) -> float:
        """Cold-start gap from the global fit (population mean when the
        regression is degenerate)."""
        if self._n < 2:
            return 1.0
        det = self._n * self._sxx - self._sx * self._sx
        if abs(det) < 1e-9:
            return math.exp(self._sy / self._n)
        w1 = (self._n * self._sxy - self._sx * self._sy) / det
        w0 = (self._sy - w1 * self._sx) / self._n
        x = math.log1p(self._touches.get(doc_id, 0))
        return max(1.0, math.exp(w0 + w1 * x))

    def predicted_gap(self, doc_id: str) -> float:
        gap = self._gap.get(doc_id)
        return gap if gap is not None else self._prior_gap(doc_id)

    def score(self, doc_id: str, now_round: int) -> float:
        """Normalized age: rounds-since-touch in units of the doc's own
        predicted rhythm. Higher = further past its working set."""
        age = now_round - self._last.get(doc_id, 0)
        return age / max(1.0, self.predicted_gap(doc_id))

    def forget(self, doc_id: str):
        """Drop per-doc state (the doc left the population entirely);
        the global fit keeps its observations — they were real."""
        self._gap.pop(doc_id, None)
        self._last.pop(doc_id, None)
        self._touches.pop(doc_id, None)

    def describe(self) -> dict:
        return {"kind": "learned", "tracked_docs": len(self._last),
                "fitted_gaps": self._n}


class LruModel:
    """The comparator heuristic: plain recency, ops as the tiebreak."""

    def __init__(self):
        self._last: dict = {}
        self._ops: dict = {}

    def note_touch(self, doc_id: str, now_round: int, n_ops: int = 1):
        self._last[doc_id] = now_round
        self._ops[doc_id] = self._ops.get(doc_id, 0) + n_ops

    def score(self, doc_id: str, now_round: int) -> float:
        age = now_round - self._last.get(doc_id, 0)
        # fewer lifetime ops nudges the score up (evict the quiet one
        # first among equally stale docs); bounded to never outweigh a
        # full round of age
        return age + 1.0 / (2.0 + self._ops.get(doc_id, 0))

    def forget(self, doc_id: str):
        self._last.pop(doc_id, None)
        self._ops.pop(doc_id, None)

    def describe(self) -> dict:
        return {"kind": "lru", "tracked_docs": len(self._last)}


def make_model(kind: str):
    return WorkingSetModel() if kind == "learned" else LruModel()


def lane_pressure(telemetry, lanes) -> list:
    """Per-lane admitted-ops totals over the retained telemetry windows
    — the SAME signal `shard/rebalance.py` reads; the page-in placement
    tiebreak (quietest lane wins among equally light ones)."""
    return [sum(v for _, v in telemetry.series(
                "shard", f"lane{lane.index}_admitted_ops"))
            for lane in lanes]

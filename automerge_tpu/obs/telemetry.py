"""Bounded rolling time-series telemetry — the *continuous* tier of
`automerge_tpu.obs` (INTERNALS §14).

The flight recorder (`obs/recorder.py`) answers "what just happened":
individual spans in a ring whose oldest records drop on wraparound. This
module answers "what has been happening": per-window aggregates fed AT
EMIT TIME, so their accuracy is independent of trace-ring retention —
the bug class ISSUE 9 closes is `metrics_snapshot()` span histograms
silently going inexact once the ring wrapped.

Three stores, all bounded, all lock-striped by writer thread id (the
recorder's Jiffy discipline: snapshots copy each stripe under its own
lock, writers are never globally paused):

- **Exact aggregates.** Per-(cat, name) span `{count, total, min, max}`
  and counter totals, updated on every emit. These never decay.
- **Log-bucketed duration histograms.** Power-of-two buckets from ~1 µs
  to ~34 s (26 buckets + overflow) per span key — enough resolution for
  conservative p50/p99 bounds at a fixed, tiny footprint.
- **Rolling windows.** A fixed ring of `n_windows` per-window aggregate
  slots (counter deltas + span count/total per key), keyed by
  `ts // window_ns`. A window older than the ring simply rolls off —
  the time-series view is bounded regardless of process lifetime.

Memory bound: `stripes × (n_windows × live keys + histogram keys)`
small dicts. Keys come from the code-defined category taxonomy
(INTERNALS §11.3), not from peers, so the key population is bounded by
the instrumentation, never by traffic. Gauges are a single small
last-value-wins dict keyed (name, labels) under one lock — gauge
populations (e.g. per-tenant lag) are bounded by their caller (the
service drops a tenant's gauges with the tenant).

Stdlib-only on purpose, like the recorder: importable on every process
start, traced or not.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

#: Stripe count (power of two: stripe selection is one mask op).
N_STRIPES = 8

#: Histogram bucket layout: bucket i covers durations in
#: (2^(LOW+i-1), 2^(LOW+i)] ns, i.e. upper bounds 2^BUCKET_LOW ns (~1 µs)
#: through 2^(BUCKET_LOW+N_BUCKETS-1) ns (~34 s); index N_BUCKETS is the
#: +Inf overflow bucket.
BUCKET_LOW = 10
N_BUCKETS = 26

#: Default window width (1 s of perf_counter time) and ring depth — a
#: bit over two minutes of continuous series at the defaults.
DEFAULT_WINDOW_NS = 1_000_000_000
DEFAULT_N_WINDOWS = 128


def bucket_index(dur_ns: int) -> int:
    """Log2 bucket for a non-negative duration. Upper bounds are
    inclusive (Prometheus ``le`` semantics): a duration of exactly
    2^k ns lands in the ``le=2^k`` bucket, not the next one up."""
    if dur_ns <= 1:
        return 0
    return min(max((dur_ns - 1).bit_length() - BUCKET_LOW, 0), N_BUCKETS)


def bucket_le_ns(i: int) -> float:
    """Upper bound (ns) of bucket `i`; +inf for the overflow bucket."""
    return float("inf") if i >= N_BUCKETS else float(1 << (BUCKET_LOW + i))


class _Stripe:
    __slots__ = ("lock", "counts", "spans", "hist", "windows")

    def __init__(self, n_windows: int):
        self.lock = threading.Lock()
        self.counts: dict = {}    # key -> exact total
        self.spans: dict = {}     # key -> [count, total_ns, min_ns, max_ns]
        self.hist: dict = {}      # key -> list[int] of N_BUCKETS + 1
        # window ring: slot (wid % n_windows) -> [wid, counts, spans]
        self.windows: list = [None] * n_windows


class Telemetry:
    """Bounded rolling telemetry store. One instance lives beside the
    flight recorder in `automerge_tpu.obs` (fed by span()/event()/
    counter() when tracing is enabled); the service tier owns a second,
    always-on instance for tick/lag series independent of tracing."""

    def __init__(self, window_ns: int = DEFAULT_WINDOW_NS,
                 n_windows: int = DEFAULT_N_WINDOWS,
                 n_stripes: int = N_STRIPES):
        if n_stripes < 1 or n_stripes & (n_stripes - 1):
            raise ValueError("n_stripes must be a power of two")
        if window_ns < 1 or n_windows < 1:
            raise ValueError("window_ns and n_windows must be >= 1")
        self.window_ns = window_ns
        self.n_windows = n_windows
        self._mask = n_stripes - 1
        self._stripes = [_Stripe(n_windows) for _ in range(n_stripes)]
        self._gauge_lock = threading.Lock()
        self._gauges: dict = {}   # (name, labels-tuple) -> value
        self.t0_ns = time.perf_counter_ns()

    # -- write side (hot when tracing is on) -----------------------------

    def _window(self, s: _Stripe, ts_ns: int) -> Optional[list]:
        wid = ts_ns // self.window_ns
        slot = wid % self.n_windows
        w = s.windows[slot]
        if w is None or w[0] != wid:
            if w is not None and w[0] > wid:
                # stale observation from before the ring's horizon (e.g.
                # a span longer than the whole ring): its window already
                # rolled off — drop it rather than clobber the live slot
                return None
            w = s.windows[slot] = [wid, {}, {}]   # roll: old window drops
        return w

    def observe_span(self, cat: str, name: str, dur_ns: int,
                     ts_ns: Optional[int] = None):
        """Fold one completed span into the exact aggregates, the log
        histogram, and the current window. Called at emit time — never
        derived from retained ring records."""
        if ts_ns is None:
            ts_ns = time.perf_counter_ns()
        key = (cat, name)
        s = self._stripes[threading.get_ident() & self._mask]
        with s.lock:
            agg = s.spans.get(key)
            if agg is None:
                s.spans[key] = [1, dur_ns, dur_ns, dur_ns]
            else:
                agg[0] += 1
                agg[1] += dur_ns
                if dur_ns < agg[2]:
                    agg[2] = dur_ns
                if dur_ns > agg[3]:
                    agg[3] = dur_ns
            h = s.hist.get(key)
            if h is None:
                h = s.hist[key] = [0] * (N_BUCKETS + 1)
            h[bucket_index(dur_ns)] += 1
            w = self._window(s, ts_ns)
            if w is not None:
                wagg = w[2].get(key)
                if wagg is None:
                    w[2][key] = [1, dur_ns]
                else:
                    wagg[0] += 1
                    wagg[1] += dur_ns

    def observe_count(self, cat: str, name: str, n: int = 1,
                      ts_ns: Optional[int] = None):
        """Bump a counter: exact total plus this window's delta."""
        if ts_ns is None:
            ts_ns = time.perf_counter_ns()
        key = (cat, name)
        s = self._stripes[threading.get_ident() & self._mask]
        with s.lock:
            s.counts[key] = s.counts.get(key, 0) + n
            w = self._window(s, ts_ns)
            if w is not None:
                w[1][key] = w[1].get(key, 0) + n

    def set_gauge(self, name: str, value, **labels):
        """Last-value-wins gauge (lag tables, occupancy levels)."""
        with self._gauge_lock:
            self._gauges[(name, tuple(sorted(labels.items())))] = value

    def drop_gauge(self, name: str, **labels):
        with self._gauge_lock:
            self._gauges.pop((name, tuple(sorted(labels.items()))), None)

    # -- read side (merges stripes; never blocks writers globally) -------

    def counters(self) -> dict:
        """Exact counter totals: {(cat, name): n} — independent of both
        the window ring and the trace ring."""
        out: dict = {}
        for s in self._stripes:
            with s.lock:
                items = list(s.counts.items())
            for k, v in items:
                out[k] = out.get(k, 0) + v
        return out

    def span_view(self) -> tuple:
        """Consistent ``(histograms, span_aggregates)`` pair: each
        stripe's hist and spans are copied under ONE lock acquisition,
        and every emit updates both under that same lock — so a span is
        either in both views or in neither. The histogram bucket total
        therefore always equals the aggregate count, the invariant a
        Prometheus histogram exposition (+Inf bucket == ``_count``)
        requires even while writers keep emitting."""
        hists: dict = {}
        aggs: dict = {}
        for s in self._stripes:
            with s.lock:
                h_items = [(k, list(v)) for k, v in s.hist.items()]
                a_items = [(k, list(v)) for k, v in s.spans.items()]
            for k, buckets in h_items:
                acc = hists.get(k)
                if acc is None:
                    hists[k] = buckets
                else:
                    for i, b in enumerate(buckets):
                        acc[i] += b
            for k, (n, tot, lo, hi) in a_items:
                agg = aggs.get(k)
                if agg is None:
                    aggs[k] = {"count": n, "total_ns": tot,
                               "min_ns": lo, "max_ns": hi}
                else:
                    agg["count"] += n
                    agg["total_ns"] += tot
                    agg["min_ns"] = min(agg["min_ns"], lo)
                    agg["max_ns"] = max(agg["max_ns"], hi)
        return hists, aggs

    def span_aggregates(self) -> dict:
        """Exact per-key span aggregates fed at emit time:
        {(cat, name): {"count", "total_ns", "min_ns", "max_ns"}}."""
        return self.span_view()[1]

    def histograms(self) -> dict:
        """Merged log-bucket counts: {(cat, name): [N_BUCKETS+1 ints]}."""
        return self.span_view()[0]

    def quantile_ns(self, cat: str, name: str, p: float) -> float:
        """Conservative quantile bound from the log histogram: the upper
        edge of the bucket holding the nearest-rank sample (the overflow
        bucket answers with the exact tracked max). 0.0 when the key has
        no samples."""
        key = (cat, name)
        hist = self.histograms().get(key)
        if not hist:
            return 0.0
        total = sum(hist)
        if total == 0:
            return 0.0
        rank = max(1, -(-int(p * total * 1000) // 1000))  # ceil, fp-safe
        rank = min(rank, total)
        seen = 0
        for i, n in enumerate(hist):
            seen += n
            if seen >= rank:
                if i >= N_BUCKETS:
                    agg = self.span_aggregates().get(key)
                    return float(agg["max_ns"]) if agg else float("inf")
                return bucket_le_ns(i)
        return bucket_le_ns(N_BUCKETS - 1)

    def windows(self) -> list:
        """The retained rolling windows, oldest first, stripes merged:
        [{"window": wid, "start_ns": wid*window_ns,
          "counters": {(cat, name): delta},
          "spans": {(cat, name): {"count", "total_ns"}}}]."""
        merged: dict = {}
        for s in self._stripes:
            with s.lock:
                parts = [(w[0], dict(w[1]),
                          {k: list(v) for k, v in w[2].items()})
                         for w in s.windows if w is not None]
            for wid, counts, spans in parts:
                m = merged.setdefault(wid, [{}, {}])
                for k, v in counts.items():
                    m[0][k] = m[0].get(k, 0) + v
                for k, (n, tot) in spans.items():
                    sp = m[1].get(k)
                    if sp is None:
                        m[1][k] = [n, tot]
                    else:
                        sp[0] += n
                        sp[1] += tot
        out = []
        # a slot that never got reused still holds its old window — drop
        # anything more than one ring span behind the newest, so the
        # returned series spans at most n_windows windows
        cutoff = (max(merged) - self.n_windows) if merged else 0
        for wid in sorted(merged):
            if wid <= cutoff:
                continue
            counts, spans = merged[wid]
            out.append({"window": wid, "start_ns": wid * self.window_ns,
                        "counters": counts,
                        "spans": {k: {"count": n, "total_ns": tot}
                                  for k, (n, tot) in spans.items()}})
        return out

    def series(self, cat: str, name: str, field: str = "counters") -> list:
        """One key's rolling series: [(start_ns, value)] per retained
        window — counter deltas (`field="counters"`) or span counts
        (`field="spans"`)."""
        key = (cat, name)
        out = []
        for w in self.windows():
            if field == "counters":
                if key in w["counters"]:
                    out.append((w["start_ns"], w["counters"][key]))
            else:
                if key in w["spans"]:
                    out.append((w["start_ns"], w["spans"][key]["count"]))
        return out

    def gauges(self) -> dict:
        """{(name, ((label, value), ...)): value} snapshot."""
        with self._gauge_lock:
            return dict(self._gauges)

    def clear(self):
        for s in self._stripes:
            with s.lock:
                s.counts = {}
                s.spans = {}
                s.hist = {}
                s.windows = [None] * self.n_windows
        with self._gauge_lock:
            self._gauges = {}

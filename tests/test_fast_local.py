"""Write-behind local fast path (backend/device.py:_try_fast_local).

Small local rounds in the interactive shapes (typing runs, delete runs,
single sets) are served host-side with op-wise diffs and replayed into the
engine later (INTERNALS §4.8). These tests pin:

- oracle parity on randomized interleavings of fast-shaped local edits,
  remote merges (flush boundaries), undo/redo, and save/load;
- that the fast path actually serves the interactive shapes (pending grows)
  and that remote deliveries flush it;
- that a remote delivery arriving between local rounds still gets full
  concurrency resolution (the add-wins case that must NOT ride the
  fast path).
"""

import random

import automerge_tpu as am
from automerge_tpu import Text
from automerge_tpu import frontend as Frontend
from automerge_tpu.backend import facade as oracle_backend
from automerge_tpu.backend.device import DeviceBackendState


def _core(doc):
    state = Frontend.get_backend_state(doc)
    assert isinstance(state, DeviceBackendState)
    return state._core


def fingerprint(doc):
    return (am.to_json(doc),
            {k: am.get_conflicts(doc, k) for k in am.to_json(doc)})


def oracle_twin(doc):
    """Replay the document's full history into an oracle-backed doc."""
    twin = am.init({"actorId": "twin",
                    "backend": oracle_backend.Backend})
    return am.apply_changes(twin, am.get_all_changes(doc))


def test_typing_run_rides_fast_path_and_merge_flushes():
    doc = am.change(am.init("aaaa"),
                    lambda d: d.__setitem__("t", Text("hello world")))
    for i in range(5):
        doc = am.change(doc, lambda d, i=i: d["t"].insert_at(5 + i, "X"))
    core = _core(doc)
    assert len(core.pending) == 5          # all five rode the fast path
    assert str(doc["t"]) == "helloXXXXX world"
    peer = am.apply_changes(am.init("bbbb"), am.get_all_changes(doc))
    peer = am.change(peer, lambda d: d["t"].insert_at(0, "Q"))
    merged = am.merge(doc, peer)           # remote delivery -> flush
    assert _core(merged).pending == []
    assert str(merged["t"]) == "QhelloXXXXX world"
    assert am.to_json(oracle_twin(merged)) == am.to_json(merged)


def test_delete_and_set_shapes_ride_fast_path():
    doc = am.change(am.init("aaaa"),
                    lambda d: d.__setitem__("t", Text("abcdef")))
    doc = am.change(doc, lambda d: [d["t"].delete_at(1),
                                    d["t"].delete_at(1)])
    doc = am.change(doc, lambda d: d["t"].set(0, "A"))
    core = _core(doc)
    assert len(core.pending) == 2
    assert str(doc["t"]) == "Adef"
    # save/load replays the full (already-admitted) history
    assert am.to_json(am.load(am.save(doc)))["t"] == "Adef"


def test_concurrent_delete_does_not_ride_fast_path():
    """The add-wins case: a concurrent remote delete looks like the next
    change but must take the engine path (covering checks)."""
    a = am.change(am.init("aaaa"),
                  lambda d: d.__setitem__("t", Text("xyz")))
    b = am.apply_changes(am.init("bbbb"), am.get_all_changes(a))
    a2 = am.change(a, lambda d: d["t"].delete_at(1))
    b2 = am.change(b, lambda d: d["t"].set(1, "Y"))   # concurrent: add-wins
    m1, m2 = am.merge(a2, b2), am.merge(b2, a2)
    assert str(m1["t"]) == str(m2["t"]) == "xYz"


def test_undo_redo_of_fast_rounds():
    doc = am.change(am.init("aaaa"),
                    lambda d: d.__setitem__("t", Text("base")))
    doc = am.change(doc, lambda d: d["t"].insert_at(4, *"123"))
    assert len(_core(doc).pending) >= 1
    assert str(doc["t"]) == "base123"
    doc = am.undo(doc)
    assert str(doc["t"]) == "base"
    doc = am.redo(doc)
    assert str(doc["t"]) == "base123"
    doc = am.change(doc, lambda d: d["t"].delete_at(0))
    doc = am.undo(doc)
    assert str(doc["t"]) == "base123"


def test_stale_state_fork_replays_fast_rounds():
    doc = am.change(am.init("aaaa"),
                    lambda d: d.__setitem__("t", Text("fork")))
    doc2 = am.change(doc, lambda d: d["t"].insert_at(0, "A"))
    # branch from the OLD state: the core forks by replay, including the
    # pending fast round bookkeeping
    branch = am.change(doc, lambda d: d["t"].insert_at(4, "Z"))
    assert str(doc2["t"]) == "Afork"
    assert str(branch["t"]) == "forkZ"


def test_randomized_interleaving_matches_oracle():
    for seed in range(4):
        rng = random.Random(52_000 + seed)
        base = am.change(am.init("base"),
                         lambda d: d.__setitem__("t", Text("seedtext")))
        base_changes = am.get_all_changes(base)
        docs = [am.apply_changes(am.init(f"actor-{i}"), base_changes)
                for i in range(2)]
        for _ in range(12):
            i = rng.randrange(2)

            def edit(d, rng=rng):
                t = d["t"]
                r = rng.random()
                if r < 0.5 or len(t) == 0:
                    at = rng.randint(0, len(t))
                    t.insert_at(at, *rng.choice(["a", "bc", "xyz"]))
                elif r < 0.75:
                    at = rng.randrange(len(t))
                    k = min(rng.randint(1, 3), len(t) - at)
                    for _ in range(k):
                        t.delete_at(at)
                else:
                    t.set(rng.randrange(len(t)), "S")
            docs[i] = am.change(docs[i], edit)
            if rng.random() < 0.2 and am.can_undo(docs[i]):
                docs[i] = am.undo(docs[i])
            if rng.random() < 0.3:
                j = 1 - i
                docs[i] = am.merge(docs[i], docs[j])
        merged = am.merge(docs[0], docs[1])
        merged2 = am.merge(docs[1], docs[0])
        assert str(merged["t"]) == str(merged2["t"]), f"seed {seed}"
        twin = oracle_twin(merged)
        assert fingerprint(twin) == fingerprint(merged), f"seed {seed}"
        # elemId-level parity, not just text
        assert [e["elemId"] for e in merged["t"].elems] == \
            [e["elemId"] for e in twin["t"].elems], f"seed {seed}"


def test_ineligible_plan_does_not_leave_stale_overlay():
    """A change that matches a fast shape but fails planning (e.g.
    non-contiguous deletes) takes the device path; the overlay built
    during the attempt must not survive stale, or the NEXT fast round
    would emit diffs against pre-device-apply positions."""
    doc = am.change(am.init("aaaa"),
                    lambda d: d.__setitem__("t", Text("abcdef")))
    # non-contiguous deletes in one change: del at 0 and (after shift) 2
    doc = am.change(doc, lambda d: [d["t"].delete_at(0),
                                    d["t"].delete_at(2)])
    assert str(doc["t"]) == "bcef"
    # next fast-shaped round must see the post-delete state
    doc = am.change(doc, lambda d: d["t"].set(2, "Z"))
    assert str(doc["t"]) == "bcZf"
    doc = am.change(doc, lambda d: d["t"].insert_at(4, *"!!"))
    assert str(doc["t"]) == "bcZf!!"
    twin = oracle_twin(doc)
    assert [e["elemId"] for e in doc["t"].elems] == \
        [e["elemId"] for e in twin["t"].elems]


def test_map_rounds_ride_fast_path():
    """Register edits on nested maps/tables (the board shape) are served
    host-side; a link-overwriting round is NOT (reachability must stay
    frozen while overlays live)."""
    doc = am.change(am.init("aaaa"), lambda d: d.update(
        {"cards": [{"title": "c0", "meta": {"votes": 1}}], "top": 1}))
    base_pending = len(_core(doc).pending)
    doc = am.change(doc, lambda d: d["cards"][0].__setitem__("title", "t2"))
    doc = am.change(doc, lambda d: d["cards"][0]["meta"]
                    .__setitem__("votes", 5))
    doc = am.change(doc, lambda d: d.__setitem__("top", 2))
    core = _core(doc)
    assert len(core.pending) == base_pending + 3   # all three rode fast
    j = am.to_json(doc)
    assert j["cards"][0]["title"] == "t2"
    assert j["cards"][0]["meta"]["votes"] == 5 and j["top"] == 2
    # deleting a key that HOLDS A LINK must take the device path
    doc = am.change(doc, lambda d: d.__delitem__("cards"))
    assert "cards" not in am.to_json(doc)
    twin = oracle_twin(doc)
    assert am.to_json(twin) == am.to_json(doc)


def test_map_undo_of_fast_rounds():
    doc = am.change(am.init("aaaa"), lambda d: d.update({"k": 1}))
    doc = am.change(doc, lambda d: d.__setitem__("k", 2))
    assert _core(doc).pending
    doc = am.undo(doc)
    assert am.to_json(doc)["k"] == 1
    doc = am.redo(doc)
    assert am.to_json(doc)["k"] == 2


def test_randomized_map_interleaving_matches_oracle():
    for seed in range(3):
        rng = random.Random(63_000 + seed)
        base = am.change(am.init("base"), lambda d: d.update(
            {"m": {"a": 1}, "t": Text("xy")}))
        base_changes = am.get_all_changes(base)
        docs = [am.apply_changes(am.init(f"actor-{i}"), base_changes)
                for i in range(2)]
        for _ in range(10):
            i = rng.randrange(2)

            def edit(d, rng=rng):
                r = rng.random()
                if r < 0.4:
                    d["m"][rng.choice("abc")] = rng.randrange(100)
                elif r < 0.55 and len(d["m"]) > 1:
                    ks = [k for k in d["m"] if k != "a"]
                    if ks:
                        del d["m"][rng.choice(ks)]
                elif r < 0.8:
                    t = d["t"]
                    t.insert_at(rng.randint(0, len(t)), rng.choice("pq"))
                else:
                    d[rng.choice("xyz")] = rng.randrange(10)
            docs[i] = am.change(docs[i], edit)
            if rng.random() < 0.3:
                docs[i] = am.merge(docs[i], docs[1 - i])
        merged = am.merge(docs[0], docs[1])
        merged2 = am.merge(docs[1], docs[0])
        twin = oracle_twin(merged)
        assert am.to_json(merged) == am.to_json(merged2) \
            == am.to_json(twin), f"seed {seed}"


class TestFastRemote:
    """Remote deliveries that causally cover the whole current document
    ride the write-behind fast path (device.py _try_fast_remote); anything
    concurrent must take the engine. Both sides pinned against the oracle."""

    def test_covering_remote_stream_matches_oracle(self):
        author = am.change(am.init("author"),
                           lambda d: d.__setitem__("t", am.Text("x" * 200)))
        peer = am.merge(am.init("peer"), author)
        doc = author
        for k in range(12):
            doc = am.change(doc, lambda d, k=k: d["t"]
                            .insert_at(10 + k, *"ab"))
        remote = am.get_all_changes(doc)[
            len(am.get_all_changes(author)):]
        for ch in remote:                     # one-by-one: the sync shape
            before = len(_core(peer).pending)
            peer = am.apply_changes(peer, [ch])
            # the covering delivery actually RODE the fast path (a gate
            # regression falling back to the engine must fail here, not
            # just silently lose the 14x)
            assert len(_core(peer).pending) == before + 1
        assert str(am.to_json(peer)["t"]) == str(am.to_json(doc)["t"])
        twin = oracle_twin(peer)
        assert am.to_json(twin) == am.to_json(peer)

    def test_concurrent_remote_delivery_keeps_engine_semantics(self):
        """A delivery that does NOT cover the receiver (receiver has its
        own concurrent edits) must resolve through the engine: conflicts
        and RGA ordering identical to the oracle in both merge orders."""
        base = am.change(am.init("base"),
                         lambda d: (d.__setitem__("t", am.Text("seed")),
                                    d.__setitem__("k", 0)))
        bc = am.get_all_changes(base)
        a = am.apply_changes(am.init("actor-a"), bc)
        b = am.apply_changes(am.init("actor-b"), bc)
        a = am.change(a, lambda d: (d["t"].insert_at(2, "A"),
                                    d.__setitem__("k", 1)))
        b = am.change(b, lambda d: (d["t"].insert_at(2, "B"),
                                    d.__setitem__("k", 2)))
        a_changes = am.get_all_changes(a)[len(bc):]
        b_changes = am.get_all_changes(b)[len(bc):]
        # deliver b's concurrent change into a one-by-one, and vice versa
        for ch in b_changes:
            a = am.apply_changes(a, [ch])
        for ch in a_changes:
            b = am.apply_changes(b, [ch])
        assert am.to_json(a) == am.to_json(b)
        assert am.to_json(a)["k"] == 2        # actor-b outranks actor-a
        assert am.get_conflicts(a, "k") == {"actor-a": 1}
        twin = oracle_twin(a)
        assert am.to_json(twin) == am.to_json(a)

    def test_remote_fast_path_not_undoable_at_receiver(self):
        author = am.change(am.init("author"),
                           lambda d: d.__setitem__("t", am.Text("hi")))
        peer = am.merge(am.init("peer"), author)
        doc = am.change(author, lambda d: d["t"].insert_at(2, "!"))
        ch = am.get_all_changes(doc)[-1]
        peer = am.apply_changes(peer, [ch])
        assert not am.can_undo(peer)          # remote ops never undoable


def test_redo_rides_fast_path_and_matches_oracle():
    """Redo re-asserts the undone field set as a run of `set` ops on
    TOMBSTONED elements — the set_run visibility-flip shape
    (device.py _fast_execute). Undo/redo chains on a large doc must stay
    sub-engine-cost and bit-identical to the oracle."""
    doc = am.change(am.init("u"),
                    lambda d: d.__setitem__("t", am.Text("x" * 500)))
    for i in range(6):
        doc = am.change(doc, lambda d, i=i: d["t"]
                        .insert_at(50 + i, *"ab"))
    for _ in range(4):
        doc = am.undo(doc)
    for _ in range(4):
        before = len(_core(doc).pending)
        doc = am.redo(doc)
        assert len(_core(doc).pending) == before + 1   # set_run fast path
    ref = am.change(am.init("v"),
                    lambda d: d.__setitem__("t", am.Text("x" * 500)))
    for i in range(6):
        ref = am.change(ref, lambda d, i=i: d["t"]
                        .insert_at(50 + i, *"ab"))
    assert str(am.to_json(doc)["t"]) == str(am.to_json(ref)["t"])
    twin = oracle_twin(doc)
    assert am.to_json(twin) == am.to_json(doc)
    # undo/redo/merge interleavings converge after the flips
    peer = am.merge(am.init("w"), doc)
    peer = am.change(peer, lambda d: d["t"].delete_at(0, 3))
    m1, m2 = am.merge(doc, peer), am.merge(peer, doc)
    assert am.to_json(m1) == am.to_json(m2)


def test_duplicate_tombstone_reassert_matches_oracle():
    """Protocol-level: one covering remote change setting the SAME
    tombstoned elemId twice. The first set flips it visible (insert
    diff); the second must index one right of the visibility snapshot
    (bisect_right over the run's flips, device.py _fast_execute)."""
    from automerge_tpu.backend import facade as oracle_backend

    author = am.change(am.init("author"),
                       lambda d: d.__setitem__("t", am.Text("abcde")))
    author = am.change(author, lambda d: d["t"].delete_at(2))
    peer = am.merge(am.init("peer"), author)
    hist = am.get_all_changes(author)
    del_op = [op for ch in hist for op in ch["ops"]
              if op["action"] == "del"][0]
    crafted = {"actor": "zzz", "seq": 1,
               "deps": dict(am.frontend.get_backend_state(author).clock),
               "ops": [{"action": "set", "obj": del_op["obj"],
                        "key": del_op["key"], "value": "X"},
                       {"action": "set", "obj": del_op["obj"],
                        "key": del_op["key"], "value": "Y"}]}
    dev = am.apply_changes(peer, [crafted])
    ora = am.apply_changes(
        am.init({"actorId": "obs", "backend": oracle_backend.Backend}),
        hist + [crafted])
    assert str(am.to_json(dev)["t"]) == str(am.to_json(ora)["t"]) == "abYde"

"""Shared machinery for device-resident CRDT documents.

Both device engines (text/list: `text_doc.py`, map/counter: `map_doc.py`)
share the host-side orchestration the reference implements per-op in
`backend/op_set.js`:

- causal admission: changes schedule into causally-ready rounds against a
  host vector clock, with queueing of unready changes and idempotent
  duplicate skips (`applyQueuedOps`/`causallyReady`,
  /root/reference/backend/op_set.js:20-27,329-345)
- order-preserving actor interning: actor-id strings map to dense ranks in
  lexicographic order, so int32 comparisons on device reproduce the
  reference's string tie-breaks (op_set.js:245,432-436)
- the slow register path: multi-writer LWW registers, counter increments,
  and deletions resolve on the host against the conflict/value-pool state
  (`applyAssign`, op_set.js:196-258) — the device flags them, the host
  resolves, one scatter writes the winners back.

Subclasses implement `_ingest(batch, mask)` (one causally-ready round ->
device programs) and `_remap_device(remap)` (re-rank actor columns after an
interning order change).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .._common import KIND_DEL, KIND_INC, KIND_SET
from .. import obs
from . import accounting
from . import learned_index

import threading


def columnar_plan_enabled() -> bool:
    """The columnar planner (INTERNALS §10) is the default; the legacy
    per-change planner stays available as the parity comparator behind
    ``AMTPU_COLUMNAR_PLAN=0`` (read per call so tests can pin either
    path)."""
    return os.environ.get("AMTPU_COLUMNAR_PLAN", "1") != "0"


class _GroupedRound(list):
    """A causally-ready round already in grouped-column form: a list of
    ``(batch, rows_arr, mask)`` triples (the shape `_group_round`
    produces), emitted directly by the columnar scheduler so no
    per-change ``(batch, row)`` tuples ever materialize on the planning
    hot path. `_group_round` passes instances through untouched."""

    __slots__ = ()


def _round_row_pairs(ready) -> set:
    """(actor, seq) pairs of one round, either representation."""
    if isinstance(ready, _GroupedRound):
        out: set = set()
        for b, rows_arr, _ in ready:
            actors = b.actors
            seqs = b.seqs
            out.update((actors[r], int(seqs[r])) for r in rows_arr.tolist())
        return out
    return {(b.actors[r], int(b.seqs[r])) for b, r in ready}

# thread-local accounting region: commit_prepared opens one so its
# per-batch delta counts ONLY the commit's own device interactions — a
# pipeline worker's concurrent prepare barriers on the same document
# must not bleed into the committed batch's budget
_ACCT_TLS = threading.local()


@dataclass
class PreparedBatch:
    """A batch planned + staged against a document's current state.

    Produced by `CausalDeviceDoc.prepare_batch`, consumed exactly once by
    `commit_prepared`. Holds the admission plan (causal rounds) and each
    round's staged device inputs, so the commit path is pure bookkeeping +
    kernel dispatch — all host->device byte movement already happened.
    This is the engine's ingestion pipelining seam: prepare batch k+1
    (host planning + transfers) while the device still executes batch k.

    A plan prepared with `after=` (a pending, not-yet-committed base plan)
    is CHAINED: it was planned against the base plan's post-commit shadow
    state, carries `after`, and commits only when the base plan committed
    and nothing else mutated the document since (committed_gen check) —
    the seam `engine/pipeline.PipelinedIngestor` uses to plan batch k+1
    on a background thread while batch k commits."""

    gen: Optional[int]        # document generation the plan is valid for
    rounds: list              # [(batch, rows_arr, book, exec_plan), ...]
    #   book = ([(actor, seq), ...], [allDeps closure, ...]) per round group
    queue_after: list         # queue state once the batch is admitted
    prior_queue: list         # queue state to restore on failure
    memo_overlay: dict        # closure-memo entries minted while planning
    n_staged_bytes: int       # total bytes shipped host->device at prepare
    after: Optional["PreparedBatch"] = None  # chained base plan (pending)
    final_shadow: Optional[tuple] = None     # shadow state post this plan
    clock_after: dict = field(default_factory=dict)  # clock post this plan
    deps_overlay: dict = field(default_factory=dict)  # (actor, seq)->closure
    committed_gen: Optional[int] = None      # _gen right after commit


def transitive_closure(all_deps: dict, actor: str, seq: int,
                       deps: dict) -> dict:
    """allDeps of a change: its explicit deps plus its own predecessor,
    closed transitively over the (actor, seq) -> clock map (the reference's
    `transitiveDeps`, /root/reference/backend/op_set.js:29-37)."""
    base = dict(deps)
    if seq > 1:
        base[actor] = seq - 1
    out: dict = {}
    for dep_actor, dep_seq in base.items():
        if dep_seq <= 0:
            continue
        transitive = all_deps.get((dep_actor, dep_seq))
        if transitive:
            for a, s in transitive.items():
                if s > out.get(a, 0):
                    out[a] = s
        out[dep_actor] = dep_seq
    return out


# batches below this use the per-change admission loop: the numpy column
# setup costs more than the walk at small sizes (tests monkeypatch it to
# force either path for parity pinning)
_BULK_SCHEDULE_MIN = 64


class CausalDeviceDoc:
    """Base: causal batch admission + registers + actor interning."""

    batch_type = None  # subclass: columnar batch class (has .from_changes)

    # Streaming-tier knobs (INTERNALS §9). `donate_buffers` selects the
    # *_donated kernel twins (ops/ingest.py) so steady-state device
    # allocation stays flat across a pipeline ring — opt-in because a
    # donated input buffer is DEAD after the kernel: the checkpoint
    # writer's zero-copy grab (checkpoint/engine_codec.grab) holds raw
    # table references and must degrade to its commit-boundary sync path
    # while donation is on. `packed_residual_writeback` ships the host
    # slow-register resolution back as ONE (6, S) matrix instead of six
    # per-column arrays (one h2d transfer; the legacy path is the parity
    # comparator, tests/test_dispatch_budget.py).
    donate_buffers = False
    packed_residual_writeback = True
    # `fused_rounds` opts a doc OUT of the ISSUE-17 fused-round kernels
    # (ops/fused_round.py) when set False; the effective switch is this
    # attribute AND the AMTPU_FUSED_ROUNDS env gate (read per round so
    # the A/B harness and parity tests flip legs without rebuilding
    # docs). With fusion off, rounds run the verbatim XLA program path —
    # the byte-identical parity comparator.
    fused_rounds = True

    def __init__(self, obj_id: str):
        self.obj_id = obj_id
        self.actor_table: list = []           # rank -> actor id (lex-ordered)
        self._actor_rank: dict = {}
        self.clock: dict = {}                 # actor id -> seq
        self._all_deps: dict = {}             # (actor, seq) -> allDeps dict
        self._closure_memo: dict = {}         # frozen base deps -> allDeps
        self.queue: list = []                 # (batch, row) not causally ready
        self.conflicts: dict = {}             # slot -> extra surviving ops
        self.value_pool: list = []            # rich values (non-inline)
        self._dev: Optional[dict] = None      # device arrays (lazy)
        self._host: Optional[dict] = None     # numpy mirrors (lazy)
        self._device_lost = False             # a donated-buffer commit
        # raised AFTER consuming the live tables: no valid device state
        # remains, so every later access fails loudly via
        # _check_device_alive (recovery = checkpoint restore or replay;
        # INTERNALS §9 donation invariants)
        self._acct = {"dispatches": 0, "syncs": 0,
                      "h2d_bytes": 0, "d2h_bytes": 0}  # device-interaction
        # counters (engine/accounting.py): every jitted program launch,
        # every blocking d2h sync, and the exact staged bytes each way
        # (ISSUE 15) this document performs
        self.last_commit_stats: Optional[dict] = None  # delta of the most
        # recent commit_prepared (the pipeline ring's per-batch budget)
        self._gen = 0                         # bumps on every state mutation
        self._intern_gen = 0                  # bumps when the actor table /
        # rank mapping changes: the validity token of every batch-level
        # rank cache (wire_columns.ColumnarChangeBatch.rank_cache)
        self._busy = 0                        # >0 while a mutation is in
        # flight: generation stamps alone cannot expose a mutation that
        # SPANS an observer's whole read (the gen bump lands at the end),
        # so content-mutating entry points raise this first and drop it
        # last — the checkpoint writer's optimistic grab treats any
        # nonzero observation as a conflict (checkpoint/engine_codec)

    def _check_device_alive(self):
        """Loud gate every _ensure_dev passes through: a donated-buffer
        commit that raised after consuming the live tables leaves NO
        valid device state — resurrecting empty tables would be silent
        corruption."""
        if self._device_lost:
            raise RuntimeError(
                f"device state of {self.obj_id!r} was lost: a commit with "
                "buffer donation enabled failed after its input tables "
                "were consumed. Restore from a checkpoint or replay the "
                "change log (INTERNALS §9 donation invariants)")

    # ------------------------------------------------------------------
    # dispatch/sync accounting (engine/accounting.py; INTERNALS §9)
    # ------------------------------------------------------------------

    def _count_dispatch(self, n: int = 1, label: str = None):
        accounting.record_dispatch(n, self._acct, label=label)
        region = getattr(_ACCT_TLS, "region", None)
        if region is not None:
            region["dispatches"] += n

    def _count_sync(self, n: int = 1, label: str = None, dur_ns: int = 0,
                    d2h_bytes: int = 0):
        accounting.record_sync(n, self._acct, label=label, dur_ns=dur_ns,
                               d2h_bytes=d2h_bytes)
        region = getattr(_ACCT_TLS, "region", None)
        if region is not None:
            region["syncs"] += n

    def _count_h2d(self, nbytes: int):
        accounting.record_h2d(nbytes, self._acct)

    # ------------------------------------------------------------------
    # device-resident footprint (obs/device_truth.py; INTERNALS §19)
    # ------------------------------------------------------------------

    def device_footprint(self) -> dict:
        """Device-resident bytes of this document, computed from
        dtype x shape over the live engine tables (9 for text, 5 for
        map) plus subclass extras — never a device sync; parity with the
        live ``jax.Array`` buffer sizes is pinned in
        tests/test_device_truth.py. Host-side companion state (index
        ranges, value pool, conflicts) rides along as counts so the
        footprint names where non-device memory scales."""
        table_bytes = 0
        n_tables = 0
        if self._dev is not None:
            for arr in self._dev.values():
                n = 1
                for d in arr.shape:
                    n *= int(d)
                table_bytes += n * np.dtype(arr.dtype).itemsize
                n_tables += 1
        extra = self._device_footprint_extra()
        return {
            "device_bytes": table_bytes + extra,
            "table_bytes": table_bytes,
            "n_tables": n_tables,
            "extra_bytes": extra,
            "host": {"value_pool": len(self.value_pool),
                     "conflicts": len(self.conflicts),
                     **self._host_footprint_extra()},
        }

    def _device_footprint_extra(self) -> int:
        """Subclass hook: device bytes held OUTSIDE the table dict
        (staged scalars, cached materializations)."""
        return 0

    def _host_footprint_extra(self) -> dict:
        return {}

    def _note_footprint(self):
        """Feed the always-on per-doc footprint gauge at a commit
        boundary (obs/device_truth.py peaks + prom families)."""
        from ..obs import device_truth
        if device_truth.ENABLED:
            device_truth.REGISTRY.note_footprint(
                "doc", self.obj_id, self.device_footprint()["device_bytes"])

    @property
    def dispatch_stats(self) -> dict:
        """Device-interaction counts for this document: total jitted
        program launches (`dispatches`) and blocking device->host syncs
        (`syncs`) since construction, plus the most recent
        `commit_prepared`'s delta (`last_commit`) — the quantity the
        streaming tier's per-batch budget is asserted against."""
        out = dict(self._acct)
        out["last_commit"] = (dict(self.last_commit_stats)
                              if self.last_commit_stats else None)
        return out

    # ------------------------------------------------------------------
    # actor interning (order-preserving: rank order == lexicographic order)
    # ------------------------------------------------------------------

    def _intern_actors(self, new_actors,
                       presorted: bool = False) -> Optional[np.ndarray]:
        """Add actors; if rank order changes, return the old->new remap.

        ``presorted`` asserts `new_actors` is already sorted and
        duplicate-free (the columnar batch's cached table): the missing
        scan then stays sorted by construction and the union is a linear
        merge of two sorted disjoint lists instead of re-sorting the
        whole table per batch."""
        if presorted:
            missing = [a for a in new_actors if a not in self._actor_rank]
        else:
            missing = sorted(set(a for a in new_actors
                                 if a not in self._actor_rank))
        if not missing:
            return None
        table = self.actor_table
        if not table:
            merged = list(missing)
        elif missing[0] > table[-1]:
            merged = table + missing
        elif missing[-1] < table[0]:
            merged = missing + table
        else:
            import heapq      # disjoint sorted lists: linear merge
            merged = list(heapq.merge(table, missing))
        new_rank = dict(zip(merged, range(len(merged))))
        remap = None
        if table and merged[: len(table)] != table:
            remap = np.asarray([new_rank[a] for a in table], np.int32)
        self.actor_table = merged
        self._actor_rank = new_rank
        self._intern_gen += 1
        return remap

    def _intern_batch_actors(self, b, append_only: bool = False
                             ) -> Optional[np.ndarray]:
        """Intern one batch's whole actor table.

        Uses the batch's cached presorted table when the per-change
        columns exist, and skips the scan entirely when this batch's
        ranks are already resolved against this document at the current
        interning generation (ColumnarChangeBatch.rank_cache — populated
        by the engine planners). `append_only` routes through
        `_intern_actors_append` (the chained-prepare constraint).

        The all-new prepend/append shape (a wide merge of fresh actors
        landing entirely before or after the current table — the
        headline workload) resolves ranks POSITIONALLY: the batch's
        precomputed table positions plus one offset, seeded straight
        into the rank cache, so no per-actor rank lookups run at all."""
        cols = getattr(b, "_change_columns", None)
        if cols is None:
            if append_only:
                self._intern_actors_append(b.actor_table)
                return None
            return self._intern_actors(b.actor_table)
        rc = cols.rank_cache.get(self)
        if rc is not None and rc["gen"] == self._intern_gen:
            return None         # already resolved; table unchanged since
        if append_only:
            self._intern_actors_append(cols.table_sorted, presorted=True)
            return None
        ts = cols.table_sorted
        rank = self._actor_rank
        # learned actor-rank site: the membership scan over the batch
        # table (which existing actors does it reference?) runs as ONE
        # packed position-model probe instead of per-actor dict lookups;
        # small batches keep the dict scan (model call overhead beats
        # the win below ~8 keys), and an unpackable table falls through.
        missing = None
        if len(ts) >= 8 and learned_index.site_enabled("actor_rank"):
            m = learned_index.doc_actor_model(self)
            if m is not None:
                got = learned_index.actor_positions(
                    self.actor_table, np.asarray(ts, object),
                    "actor_rank", model=m)
                if got is not None:
                    fnd = got[1]
                    missing = ([] if fnd.all() else
                               [a for a, f in zip(ts, fnd.tolist())
                                if not f])
        if missing is None:
            missing = [a for a in ts if a not in rank]
        if not missing:
            return None
        table = self.actor_table
        if len(ts) - len(missing) == len(table):
            # every existing actor appears in the batch table too, so the
            # merged table IS `ts` and ranks are the batch's precomputed
            # positions — zero per-actor rank lookups (the headline
            # shape: a wide merge referencing the document's actors)
            pos = cols.table_pos_map()
            old_pos = [pos[a] for a in table]
            remap = (np.asarray(old_pos, np.int32)
                     if old_pos != list(range(len(table))) else None)
            self.actor_table = list(ts)
            self._actor_rank = dict(zip(ts, range(len(ts))))
            self._intern_gen += 1
            tp, rp = cols.positional_ranks(b)
            cols.rank_cache[self] = {
                "gen": self._intern_gen, "batch_rank": tp, "row_rank": rp}
            return remap
        off = None
        remap = None
        if len(missing) == len(ts):
            if not table or missing[0] > table[-1]:
                off = len(table)            # append: existing ranks keep
                merged = table + missing
            elif missing[-1] < table[0]:
                off = 0                     # prepend: old ranks shift up
                merged = missing + table
                remap = np.arange(len(missing),
                                  len(missing) + len(table), dtype=np.int32)
        if off is None:                     # interleaved: general merge
            return self._intern_actors(ts, presorted=True)
        self.actor_table = merged
        self._actor_rank = dict(zip(merged, range(len(merged))))
        self._intern_gen += 1
        tp, rp = cols.positional_ranks(b)
        cols.rank_cache[self] = {
            "gen": self._intern_gen,
            "batch_rank": tp + off,
            "row_rank": (rp + off).astype(np.int32)}
        return remap

    def _apply_remap(self, remap: np.ndarray):
        self._busy += 1   # device/index/conflict columns move together
        try:
            self._remap_device(remap)
            for ops in self.conflicts.values():
                for op in ops:
                    op["actor_rank"] = int(remap[op["actor_rank"]])
            self._invalidate()
        finally:
            self._busy -= 1

    def _intern_actors_append(self, new_actors, presorted: bool = False):
        """Intern actors WITHOUT ever remapping existing ranks — the only
        interning a chained prepare may perform, because a remap would
        invalidate the pending base plan's staged actor columns. Raises
        ValueError when the new actors would not all rank after the
        current table (the caller falls back to a fresh, unchained
        prepare once the base commit lands)."""
        if presorted:
            missing = [a for a in new_actors if a not in self._actor_rank]
        else:
            missing = sorted(set(a for a in new_actors
                                 if a not in self._actor_rank))
        if not missing:
            return
        if self.actor_table and missing[0] < self.actor_table[-1]:
            raise ValueError(
                "actor interning would reorder existing ranks; cannot "
                "chain this prepare onto a pending plan")
        for a in missing:
            self._actor_rank[a] = len(self.actor_table)
            self.actor_table.append(a)
        self._intern_gen += 1

    # ------------------------------------------------------------------
    # causality
    # ------------------------------------------------------------------

    def _compute_all_deps(self, actor: str, seq: int, deps: dict,
                          all_deps=None, memo=None) -> dict:
        # batches of concurrent changes typically share one dep frontier
        # (e.g. 10k actors all depending on {base: 1}); the closure depends
        # only on the effective base dep set — (implicit self-dep, explicit
        # deps) — so memoize on that key without building the merged dict on
        # hits. Entries are treated as read-only by every consumer.
        # `all_deps`/`memo` default to the document's maps; prepare_batch
        # passes ChainMap overlays so planning stays side-effect-free.
        if all_deps is None:
            all_deps = self._all_deps
        if memo is None:
            memo = self._closure_memo
        key = ((actor, seq - 1, tuple(sorted(deps.items()))) if seq > 1
               else (None, 0, tuple(sorted(deps.items()))))
        hit = memo.get(key)
        if hit is None:
            base = dict(deps)
            if seq > 1:
                base[actor] = seq - 1
            hit = transitive_closure(all_deps, actor, 0, base)
            memo[key] = hit
        return hit

    def _causally_covers(self, all_deps: dict, op: dict) -> bool:
        if op["actor_rank"] < 0:
            return True
        return all_deps.get(self.actor_table[op["actor_rank"]], 0) >= op["seq"]

    @staticmethod
    def _shared_frontier(deps_list, rows, seqs):
        """The ONE deps dict shared (by identity) by every given row, all
        at seq 1 — the wide-concurrent-merge shape (N actors, one
        frontier) — or None. Identity is deliberate: `intern_deps`
        (columnar.py) collapses equal dicts at batch construction, so the
        common shape is recognized in O(rows) pointer compares and the
        closure/admission work collapses to a single computation. Any
        other shape falls back to the general per-row path."""
        d0 = deps_list[rows[0]]
        for r in rows:
            if seqs[r] != 1 or deps_list[r] is not d0:
                return None
        return d0

    # ------------------------------------------------------------------
    # batch application
    # ------------------------------------------------------------------

    def apply_changes(self, changes):
        return self.apply_batch(self._decode_wire(changes))

    def _decode_wire(self, changes):
        """Protocol boundary: wire changes -> columnar batch. Subclasses
        with a vectorized boundary decoder (text: wire_columns) override;
        the base decodes ops columnar and leaves the per-change columns
        to derive lazily at first schedule (equivalent — they cache on
        the batch either way)."""
        return type(self).batch_type.from_changes(changes, self.obj_id)

    def _schedule(self, batch, clock=None, prior_queue=None):
        """Admission scheduling: partition the batch + queued items into
        causally-ready rounds over a host clock (no state mutation).
        Returns (rounds, queue_after, prior_queue). `clock`/`prior_queue`
        default to the document's live state; a chained prepare passes the
        pending base plan's post-commit snapshots instead."""
        if obs.ENABLED:
            _t0 = obs.now()
            out = self._schedule_inner(batch, clock, prior_queue)
            obs.span("plan", "admission", _t0, args={
                "doc": self.obj_id, "n_changes": batch.n_changes,
                "n_rounds": len(out[0]), "queued": len(out[1])})
            return out
        return self._schedule_inner(batch, clock, prior_queue)

    def _schedule_inner(self, batch, clock=None, prior_queue=None):
        prior_queue = list(self.queue if prior_queue is None
                           else prior_queue)
        # columnar planner (default; INTERNALS §10): admission over the
        # batch's per-change struct-of-arrays — rounds come back already
        # GROUPED ((batch, rows, mask) triples), no per-change tuples.
        # Plan-equivalent to the legacy paths below by construction;
        # pinned by tests/test_columnar_plan.py.
        if not prior_queue and batch.n_changes and columnar_plan_enabled():
            out = self._schedule_columnar(
                batch, self.clock if clock is None else clock, prior_queue)
            if out is not None:
                return out
        pending = list(range(batch.n_changes)) + prior_queue
        clock = dict(self.clock if clock is None else clock)
        scheduled: set = set()  # (actor, seq) admitted in this call
        rounds: list = []
        queue_after: list = []
        batch_actors = batch.actors
        batch_seqs = batch.seqs.tolist() if batch.n_changes else []

        # fast path — wide concurrent merge: empty queue, every change at
        # seq 1 from a distinct new actor, all sharing ONE already-covered
        # dep frontier. One check admits the whole batch as one round.
        # (A frontier naming a batch actor would need the slow path's
        # self-dep skip; such an actor has clock>=1 and fails the new-actor
        # test, so the fallback is automatic.)
        if not prior_queue and batch.n_changes:
            d0 = self._shared_frontier(batch.deps, range(batch.n_changes),
                                       batch_seqs)
            if d0 is not None and all(
                    clock.get(a, 0) >= s for a, s in d0.items()):
                actor_set = set(batch_actors)
                if (len(actor_set) == batch.n_changes
                        and not (actor_set & clock.keys())):
                    return ([[(batch, r) for r in range(batch.n_changes)]],
                            [], prior_queue)
        # bulk path — any other large batch with an empty queue: admission
        # becomes numpy round passes over (actor rank, seq, deps group)
        # columns instead of a per-change Python walk per round (the
        # multi-round causal shapes, cfg5c, paid O(rounds x changes) dict
        # work here). Bit-equivalent to the loop below by construction;
        # pinned by tests/test_pipeline.py::test_schedule_bulk_parity.
        if not prior_queue and batch.n_changes >= _BULK_SCHEDULE_MIN:
            return self._schedule_bulk(batch, clock, prior_queue)
        while pending:
            ready, not_ready = [], []
            for item in pending:
                if isinstance(item, int):
                    b, row = batch, item
                    actor, seq = batch_actors[row], batch_seqs[row]
                else:
                    b, row = item
                    actor, seq = b.actors[row], int(b.seqs[row])
                if seq <= clock.get(actor, 0) or (actor, seq) in scheduled:
                    continue  # duplicate: idempotent skip (inconsistent reuse
                    # of a seq by the same actor is not detected here; the
                    # oracle backend raises on it)
                # implicit self-dep on (actor, seq-1) OVERRIDES any explicit
                # self-dep, matching the reference's causallyReady
                # (/root/reference/backend/op_set.js:20-27)
                deps = b.deps[row]
                if (seq <= 1 or clock.get(actor, 0) >= seq - 1) and all(
                        clock.get(a, 0) >= s for a, s in deps.items()
                        if a != actor):
                    ready.append((b, row))
                    scheduled.add((actor, seq))
                else:
                    not_ready.append(item if not isinstance(item, int) else (b, row))
            if not ready:
                queue_after = not_ready
                break
            for b, row in ready:
                clock[b.actors[row]] = int(b.seqs[row])
            rounds.append(ready)
            pending = not_ready
        return rounds, queue_after, prior_queue

    def _schedule_bulk(self, batch, clock0: dict, prior_queue: list):
        """Vectorized admission for a whole batch (empty prior queue).

        One numpy pass per causal ROUND instead of one Python iteration
        per change per round: rows carry dense local actor ids and a deps
        GROUP id (dep dicts interned by identity at batch construction,
        then deduplicated by content), so the per-round readiness test is
        a handful of boolean column ops plus one small loop over unique
        dep groups. Semantics are the loop path's exactly: idempotent
        duplicate skips, the implicit self-dep override, first-occurrence
        wins for same-(actor, seq) rows inside one round."""
        n = batch.n_changes
        actors = batch.actors
        seqs = np.asarray(batch.seqs, np.int64)

        aid: dict = {}
        aidx = np.empty(n, np.int64)
        for i, a in enumerate(actors):
            j = aid.get(a)
            if j is None:
                j = aid[a] = len(aid)
            aidx[i] = j

        # deps groups: identity first (intern_deps collapses equal dicts
        # at batch construction), then content-dedup the handful of
        # distinct objects so hand-built batches group too
        gid_by_id: dict = {}
        group_deps: list = []
        dgid = np.empty(n, np.int64)
        for i, d in enumerate(batch.deps):
            g = gid_by_id.get(id(d))
            if g is None:
                g = gid_by_id[id(d)] = len(group_deps)
                group_deps.append(d)
            dgid[i] = g
        by_content: dict = {}
        remap_g = np.empty(len(group_deps), np.int64)
        for g, d in enumerate(group_deps):
            remap_g[g] = by_content.setdefault(
                tuple(sorted(d.items())), g)
        dgid = remap_g[dgid]

        for d in group_deps:         # dep-referenced actors need clock rows
            for a in d:
                if a not in aid:
                    aid[a] = len(aid)
        clock = np.zeros(len(aid), np.int64)
        for a, j in aid.items():
            clock[j] = clock0.get(a, 0)
        g_actor = [np.asarray([aid[a] for a in d], np.int64)
                   for d in group_deps]
        g_seq = [np.asarray([s for _, s in d.items()], np.int64)
                 for d in group_deps]

        round_rows, remaining = self._admission_rounds(
            aidx, seqs, dgid, g_actor, g_seq, len(group_deps), clock)
        rounds = [[(batch, int(r)) for r in r_idx] for r_idx in round_rows]
        queue_after = [(batch, int(r)) for r in np.flatnonzero(remaining)]
        return rounds, queue_after, prior_queue

    @staticmethod
    def _admission_rounds(aidx, seqs, dgid, g_actor, g_seq,
                          n_groups: int, clock):
        """The ONE vectorized admission loop (one numpy pass per causal
        round) shared by `_schedule_bulk` and `_schedule_columnar` — the
        admission SEMANTICS (idempotent dup skip, implicit self-dep
        override via single-failure forgiveness, first-occurrence-wins
        for same-(actor, seq) rows in one round) live here and nowhere
        else, so the default planner and the parity comparator cannot
        drift. `clock` is mutated in place. Returns (round index arrays,
        remaining mask: rows still pending = the queue)."""
        n = len(seqs)
        round_rows: list = []
        remaining = np.ones(n, bool)
        while True:
            idxs = np.flatnonzero(remaining)
            if not len(idxs):
                break
            a_i = aidx[idxs]
            s_i = seqs[idxs]
            dup = s_i <= clock[a_i]
            if dup.any():            # idempotent skips leave pending for good
                remaining[idxs[dup]] = False
                idxs = idxs[~dup]
                a_i, s_i = a_i[~dup], s_i[~dup]
                if not len(idxs):
                    continue
            seq_ready = (s_i <= 1) | (clock[a_i] >= s_i - 1)
            # per-group dep check; a group's SINGLE failing entry is
            # forgiven for rows whose own actor it names (the implicit
            # self-dep override)
            gs = np.unique(dgid[idxs])
            n_fail = np.zeros(n_groups, np.int64)
            fail_one = np.full(n_groups, -1, np.int64)
            for g in gs:
                fa, fs = g_actor[g], g_seq[g]
                fails = fa[clock[fa] < fs]
                n_fail[g] = len(fails)
                if len(fails) == 1:
                    fail_one[g] = fails[0]
            gr = dgid[idxs]
            dep_ok = (n_fail[gr] == 0) | ((n_fail[gr] == 1)
                                          & (fail_one[gr] == a_i))
            ready = seq_ready & dep_ok
            r_idx = idxs[ready]
            if not len(r_idx):
                break
            # same-round same-(actor, seq) rows: first occurrence wins
            pairk = (aidx[r_idx] << np.int64(32)) | seqs[r_idx]
            _, first = np.unique(pairk, return_index=True)
            if len(first) != len(r_idx):
                r_idx = r_idx[np.sort(first)]
            remaining[r_idx] = False
            np.maximum.at(clock, aidx[r_idx], seqs[r_idx])
            round_rows.append(r_idx)
        return round_rows, remaining

    def _schedule_columnar(self, batch, clock0: dict, prior_queue: list):
        """Columnar admission (INTERNALS §10): rounds over the batch's
        per-change struct-of-arrays, emitted pre-grouped.

        The per-change metadata — dense actor ids, seq column, dep
        GROUPS — was derived once at the protocol boundary
        (engine/wire_columns.change_columns) and is reused across every
        application of the (immutable) batch, so admission is boolean
        column ops against a clock vector: no per-change dict lookups,
        no (batch, row) tuple lists. Returns None for shapes the columns
        do not cover (small batches without the wide-merge shape fall to
        the per-change loop, whose cost at that size is the setup's).
        Admission decisions are exactly the legacy paths' — the fast
        path tests the same frontier/new-actor conditions at dep-CONTENT
        level (the legacy identity test plus `_schedule_bulk`'s content
        dedup reach the same partition), and the bulk loop mirrors
        `_schedule_bulk` row for row."""
        n = batch.n_changes
        cols = getattr(batch, "_change_columns", None)
        if cols is None and n < _BULK_SCHEDULE_MIN:
            # tiny (interactive) batches: deriving columns costs more
            # than the per-change loop saves, and the legacy identity
            # fast path covers the small wide-merge shape equally well —
            # don't burden the cfg7 write-behind hot path
            return None
        if cols is None:
            from .wire_columns import change_columns
            cols = change_columns(batch)

        # fast path — wide concurrent merge: every change at seq 1 from a
        # distinct new actor, one already-covered dep frontier. The
        # columns make each test O(distinct) instead of O(changes).
        if cols.all_seq1 and cols.distinct_actors and cols.single_group:
            d0 = cols.group_deps[0]
            if all(clock0.get(a, 0) >= s for a, s in d0.items()):
                # new-actor test from the cheaper side: the batch's actor
                # set is a frozenset, the clock a dict — iterate whichever
                # is smaller
                if len(clock0) <= cols.n_change_actors:
                    fresh = not any(a in cols.actor_set for a in clock0)
                else:
                    fresh = not any(
                        a in clock0
                        for a in cols.local_actors[:cols.n_change_actors])
                if fresh:
                    return ([_GroupedRound(
                        [(batch, np.arange(n, dtype=np.int32),
                          slice(None))])], [], prior_queue)

        if n < _BULK_SCHEDULE_MIN:
            return None         # loop path: setup costs more than the walk

        # bulk columnar rounds — `_schedule_bulk`'s per-round vector pass
        # with every per-call derivation (dense ids, dep grouping, group
        # arrays) replaced by the batch's cached columns. Only the clock
        # vector is per-document.
        aidx = cols.actor_idx.astype(np.int64)
        seqs = cols.seqs.astype(np.int64)
        dgid = cols.dep_gid
        n_groups = len(cols.group_deps)
        clock = np.empty(len(cols.local_actors), np.int64)
        for j, a in enumerate(cols.local_actors):
            clock[j] = clock0.get(a, 0)
        g_actor = [cols.g_actor[cols.g_off[g]:cols.g_off[g + 1]]
                   .astype(np.int64) for g in range(n_groups)]
        g_seq = [cols.g_seq[cols.g_off[g]:cols.g_off[g + 1]]
                 for g in range(n_groups)]

        round_rows, remaining = self._admission_rounds(
            aidx, seqs, dgid, g_actor, g_seq, n_groups, clock)
        queue_after = [(batch, int(r)) for r in np.flatnonzero(remaining)]

        if len(round_rows) == 1 and len(round_rows[0]) == n:
            rounds = [_GroupedRound(
                [(batch, np.arange(n, dtype=np.int32), slice(None))])]
        else:
            # one pass builds every round's op mask: rounds partition the
            # admitted changes, so op masks come from a change->round map
            round_of = np.full(n, -1, np.int64)
            for k, r_idx in enumerate(round_rows):
                round_of[r_idx] = k
            op_round = round_of[batch.op_change]
            rounds = [
                _GroupedRound([(batch, r_idx.astype(np.int32),
                                op_round == k)])
                for k, r_idx in enumerate(round_rows)]
        return rounds, queue_after, prior_queue

    def apply_batch(self, batch):
        """Merge a columnar change batch (causally gated, idempotent)."""
        self._busy += 1
        try:
            return self._apply_batch(batch)
        finally:
            self._busy -= 1

    def _apply_batch(self, batch):
        rounds, queue_after, prior_queue = self._schedule(batch)
        self.queue = queue_after
        applied: set = set()
        try:
            for ready in rounds:
                self._apply_round(ready)
                applied |= _round_row_pairs(ready)
        except BaseException:
            # a failed round must not swallow changes that were queued before
            # this call: admission consumed self.queue into the round plan, so
            # put back every prior item that did not actually apply. Changes
            # delivered IN this call are dropped wholesale — the call raised,
            # so the caller redelivers (matching the reference's all-or-
            # nothing applyChanges; completed earlier rounds are the
            # documented change-granularity deviation).
            self.queue = [
                it for it in prior_queue
                if (it[0].actors[it[1]], int(it[0].seqs[it[1]])) not in applied]
            self._gen += 1  # queue changed: invalidate outstanding plans
            self._plan_failed()
            raise
        self._invalidate()
        self._note_footprint()
        return self

    @staticmethod
    def _group_round(ready) -> list:
        """Group one round's (batch, row) pairs by source batch and compute
        each group's op mask. Columnar rounds arrive pre-grouped and pass
        through untouched."""
        if isinstance(ready, _GroupedRound):
            return ready
        b0 = ready[0][0]
        if len(ready) == b0.n_changes and all(it[0] is b0 for it in ready):
            # single whole batch (the fast-schedule shape): rows are the
            # full dedeuplicated set by construction
            return [(b0, np.arange(b0.n_changes, dtype=np.int32),
                     slice(None))]
        by_batch: dict = {}
        for b, row in ready:
            by_batch.setdefault(id(b), (b, []))[1].append(row)
        groups = []
        for b, rows in by_batch.values():
            if len(rows) == b.n_changes:
                # whole batch ready (scheduler dedupes, so a full-length
                # row list IS 0..n-1): no sort, no filtering
                rows_arr = np.arange(b.n_changes, dtype=np.int32)
                mask = slice(None)
            else:
                rows_arr = np.sort(np.asarray(rows, np.int32))
                mask = np.isin(b.op_change, rows_arr)
            groups.append((b, rows_arr, mask))
        return groups

    def _frontier_pairs(self, b, rows_arr):
        """The shared-frontier decision of one round group, ONE place for
        both the apply path (`_round_bookkeeping`) and the prepare path
        (`prepare_batch`): returns (d0, pairs, rows_l, seqs_l) where a
        non-None `d0` is the single dep frontier every row shares (all at
        seq 1) and `pairs` its (actor, 1) rows — derived from the
        columnar shape flags + the batch-level pairs cache when the
        columns exist, from the identity walk otherwise. d0 None = mixed
        round; rows_l/seqs_l are the materialized lists the mixed path
        consumes (only built when actually needed)."""
        actors = b.actors
        cols = (getattr(b, "_change_columns", None)
                if columnar_plan_enabled() else None)
        if (cols is not None and len(rows_arr)
                and cols.all_seq1 and cols.single_group):
            pairs = (cols.pairs_all(actors, b.seqs)
                     if len(rows_arr) == b.n_changes
                     else [(actors[r], 1) for r in rows_arr.tolist()])
            return cols.group_deps[0], pairs, None, None
        seqs_l = b.seqs.tolist()
        rows_l = rows_arr.tolist()
        d0 = (self._shared_frontier(b.deps, rows_l, seqs_l)
              if rows_l else None)
        pairs = ([(actors[r], 1) for r in rows_l]
                 if d0 is not None else None)
        return d0, pairs, rows_l, seqs_l

    def _round_bookkeeping(self, b, rows_arr):
        """Advance clock/_all_deps for a round's rows; returns the snapshots
        `_rollback_bookkeeping` needs if the round's ingest fails."""
        clock = self.clock
        all_deps = self._all_deps
        actors, deps_list = b.actors, b.deps
        d0, pairs, rows, seqs = self._frontier_pairs(b, rows_arr)
        if d0 is not None:
            # one closure serves the whole round; bookkeeping is bulk
            # C-speed dict work (dict.fromkeys/update) per row
            hit = self._compute_all_deps(pairs[0][0], 1, d0)
            prev_clock = {a: clock.get(a) for a, _ in pairs}
            prev_deps = {p: all_deps.get(p) for p in pairs}
            all_deps.update(dict.fromkeys(pairs, hit))
            clock.update(pairs)
            return prev_clock, prev_deps
        # d0 None comes only from the identity-walk branch: rows/seqs set
        assert rows is not None

        # mixed round: closures computed grouped by shared deps dict
        # (rows of one round are causally independent, so computing every
        # closure against the PRE-round maps is equivalent to the old
        # insert-as-you-go walk), then committed as bulk dict updates
        pairs, closures = self._bulk_closures(rows, actors, seqs,
                                              deps_list, all_deps,
                                              self._closure_memo)
        prev_clock = {}
        prev_deps = {}
        for (actor, seq), hit in zip(pairs, closures):
            if actor not in prev_clock:
                prev_clock[actor] = clock.get(actor)
            prev_deps[(actor, seq)] = all_deps.get((actor, seq))
            all_deps[(actor, seq)] = hit
            clock[actor] = seq
        return prev_clock, prev_deps

    def _rollback_bookkeeping(self, snapshots):
        prev_clock, prev_deps = snapshots
        for actor, old in prev_clock.items():
            if old is None:
                self.clock.pop(actor, None)
            else:
                self.clock[actor] = old
        for key, old in prev_deps.items():
            if old is None:
                self._all_deps.pop(key, None)
            else:
                self._all_deps[key] = old
        # closures derived from the rolled-back entries are stale
        self._closure_memo.clear()

    def _apply_round(self, ready):
        """Apply causally-ready (batch, row) pairs: one device program each."""
        for b, rows_arr, mask in self._group_round(ready):
            # ops may reference ids minted by actors whose own changes sit
            # in other rounds, so intern the batch's whole actor table.
            # Interning runs BEFORE the clock advances: a raising remap then
            # leaves the causal state untouched (extra interned actors are
            # harmless — interning only renames ranks consistently, it adds
            # no document content).
            remap = self._intern_batch_actors(b)
            if remap is not None:
                self._apply_remap(remap)

            # _ingest needs clock/_all_deps populated for this round's
            # changes (the slow register path reads them), but a raising
            # _ingest must leave them untouched or a corrected redelivery
            # of the same (actor, seq) is silently skipped as a duplicate —
            # so snapshot and roll back on failure.
            snapshots = self._round_bookkeeping(b, rows_arr)
            if b.n_ops:
                try:
                    self._ingest(b, mask)
                except BaseException:
                    self._rollback_bookkeeping(snapshots)
                    raise

    # ------------------------------------------------------------------
    # two-phase ingestion (pipelining seam)
    # ------------------------------------------------------------------

    def _bulk_closures(self, rows_l, actors, seqs_l, deps_list, all_map,
                       memo_map):
        """allDeps closures for one round group's rows, grouped by shared
        deps OBJECT: seq-1 rows sharing one deps dict share one closure
        (their memo key is actor-independent), so mixed rounds pay
        per-distinct-frontier work instead of per-row closure walks.
        Returns (pairs, closures) aligned with `rows_l`'s order."""
        pairs: list = [None] * len(rows_l)
        closures: list = [None] * len(rows_l)
        by_dep: dict = {}
        for i, row in enumerate(rows_l):
            by_dep.setdefault(id(deps_list[row]), []).append(i)
        for idxs in by_dep.values():
            d = deps_list[rows_l[idxs[0]]]
            shared = None
            for i in idxs:
                row = rows_l[i]
                actor, seq = actors[row], seqs_l[row]
                if seq == 1:
                    if shared is None:
                        shared = self._compute_all_deps(
                            actor, 1, d, all_deps=all_map, memo=memo_map)
                    hit = shared
                else:
                    hit = self._compute_all_deps(
                        actor, seq, d, all_deps=all_map, memo=memo_map)
                pairs[i] = (actor, seq)
                closures[i] = hit
        return pairs, closures

    def prepare_batch(self, batch, after: Optional[PreparedBatch] = None
                      ) -> PreparedBatch:
        """Plan + stage a batch without mutating document content.

        Runs admission scheduling, per-round host planning (run detection,
        reference resolution, validity checks), and ships every device
        input buffer host->device — so `commit_prepared` is bookkeeping +
        kernel dispatch only. The only state this touches is actor
        interning, which is content-free (it renames ranks consistently).

        The plan binds to the document's current generation: any other
        mutation between prepare and commit invalidates it (commit raises
        ValueError, document unharmed). Use it to pipeline ingestion —
        prepare batch k+1 while the device executes batch k — or to move
        transfer latency off the merge critical path.

        `after=` chains this plan onto a PENDING (prepared, uncommitted)
        base plan: planning runs against the base plan's post-commit
        shadow/clock/closure state, so a background thread can prepare
        batch k+1 while the caller thread still commits batch k
        (engine/pipeline.PipelinedIngestor). A chained plan commits only
        directly after its base (commit re-checks via the base's
        committed generation). Chaining never remaps actor ranks — if the
        batch's actors would reorder the interning table, this raises
        ValueError and the caller falls back to an unchained prepare."""
        from collections import ChainMap
        _t0 = obs.now() if obs.ENABLED else 0
        chain: list = []
        if after is not None:
            if after.final_shadow is None:
                raise ValueError(
                    "cannot chain prepare onto a plan without shadow state")
            # append-only interning (raises on reorder) — a remap would
            # invalidate the pending base plan's staged actor columns
            self._intern_batch_actors(batch, append_only=True)
            p: Optional[PreparedBatch] = after
            while p is not None:
                chain.append(p)
                p = p.after
            rounds, queue_after, prior_queue = self._schedule(
                batch, clock=after.clock_after,
                prior_queue=after.queue_after)
            grounds = [self._group_round(r) for r in rounds]
            for groups in grounds:
                for b, _, _ in groups:
                    if b is not batch:
                        self._intern_batch_actors(b, append_only=True)
            gen = None
            shadow = after.final_shadow
            base_clock = after.clock_after
        else:
            remap = self._intern_batch_actors(batch)
            if remap is not None:
                self._apply_remap(remap)
            rounds, queue_after, prior_queue = self._schedule(batch)
            grounds = [self._group_round(r) for r in rounds]
            # intern queued batches' actors too, BEFORE planning: a remap
            # after a round was planned would invalidate its staged ranks
            for groups in grounds:
                for b, _, _ in groups:
                    if b is not batch:
                        remap = self._intern_batch_actors(b)
                        if remap is not None:
                            self._apply_remap(remap)
            gen = self._gen
            shadow = self._plan_shadow()
            base_clock = self.clock
        planned_rounds = []
        staged_bytes = 0
        # precompute each round's clock/deps bookkeeping (the allDeps
        # closures) so commit is dict updates only. Later rounds may depend
        # on closures of earlier rounds of this same plan — or of a pending
        # chained base plan — which are not in self._all_deps yet; thread
        # them through overlay maps.
        deps_overlay: dict = {}
        memo_overlay: dict = {}
        all_map = ChainMap(deps_overlay,
                           *[p.deps_overlay for p in chain], self._all_deps)
        memo_map = ChainMap(memo_overlay,
                            *[p.memo_overlay for p in chain],
                            self._closure_memo)
        clock_after = dict(base_clock)
        for groups in grounds:
            for b, rows_arr, mask in groups:
                actors, deps_list = b.actors, b.deps
                # ONE shared-frontier decision for apply and prepare
                # paths alike (`_frontier_pairs`): columnar shape flags +
                # the batch-level pairs cache when columns exist, the
                # identity walk otherwise
                d0, pairs, rows_l, seqs_l = self._frontier_pairs(
                    b, rows_arr)
                if d0 is not None:
                    hit = self._compute_all_deps(
                        pairs[0][0], 1, d0, all_deps=all_map,
                        memo=memo_map)
                    closures = [hit] * len(pairs)
                    deps_overlay.update(dict.fromkeys(pairs, hit))
                else:
                    pairs, closures = self._bulk_closures(
                        rows_l, actors, seqs_l, deps_list, all_map,
                        memo_map)
                    deps_overlay.update(zip(pairs, closures))
                clock_after.update(pairs)
                exec_plan = None
                if b.n_ops:
                    exec_plan, shadow = self._plan_round(b, mask, shadow)
                if exec_plan is not None:
                    staged_bytes += sum(
                        x.size * x.dtype.itemsize for x in exec_plan.staged)
                planned_rounds.append((b, rows_arr, (pairs, closures),
                                       exec_plan))
        # barrier: the prepared plan is complete only once its buffers are
        # resident (keeps commit free of transfer stalls). Counted as a
        # blocking sync — it is one — but it lands on the PREPARE side,
        # which the pipeline ring overlaps under device execution, so it
        # never appears in a commit's per-batch delta.
        import jax
        _tb = obs.now() if obs.ENABLED else 0
        jax.block_until_ready(
            [x for _, _, _, p in planned_rounds if p is not None
             for x in p.staged])
        self._count_sync(label="stage_barrier",
                         dur_ns=(obs.now() - _tb) if _tb else 0)
        # exact h2d byte meter (ISSUE 15): the plan's summed staged
        # bytes, counted once at the seam where they are already known
        self._count_h2d(staged_bytes)
        if obs.ENABLED:
            obs.span("plan", "prepare_batch", _t0, args={
                "doc": self.obj_id, "n_ops": getattr(batch, "n_ops", 0),
                "n_changes": batch.n_changes,
                "n_rounds": len(planned_rounds),
                "staged_bytes": staged_bytes,
                "chained": after is not None})
        return PreparedBatch(gen=gen, rounds=planned_rounds,
                             queue_after=queue_after,
                             prior_queue=prior_queue,
                             memo_overlay=memo_overlay,
                             n_staged_bytes=staged_bytes,
                             after=after, final_shadow=shadow,
                             clock_after=clock_after,
                             deps_overlay=deps_overlay)

    def commit_prepared(self, prepared: PreparedBatch):
        """Commit a `prepare_batch` plan: clock/deps bookkeeping + staged
        kernel dispatch. Raises ValueError (document untouched) if the
        document mutated since the plan was prepared — for a chained plan,
        if its base plan has not committed or anything mutated since."""
        self._busy += 1
        # thread-local region: the delta counts the COMMIT's own device
        # interactions only — concurrent worker-thread prepares against
        # this doc (the pipeline ring) update the doc totals but not this
        region = {"dispatches": 0, "syncs": 0}
        prior_region = getattr(_ACCT_TLS, "region", None)
        _ACCT_TLS.region = region
        n_rounds = len(prepared.rounds)     # severed on success — read now
        _t0 = obs.now() if obs.ENABLED else 0
        try:
            out = self._commit_prepared(prepared)
        finally:
            self._busy -= 1
            _ACCT_TLS.region = prior_region
            if obs.ENABLED:
                obs.span("commit", "batch", _t0, args={
                    "doc": self.obj_id, "n_rounds": n_rounds,
                    "gen": self._gen, **region})
        # per-committed-batch device-interaction delta: the quantity the
        # streaming tier budgets (asserted <= a small constant on the
        # write-behind path; carried in bench --pipeline records)
        self.last_commit_stats = {**region, "n_rounds": n_rounds}
        self._note_footprint()
        return out

    def _commit_prepared(self, prepared: PreparedBatch):
        if prepared.committed_gen is not None:
            raise ValueError("prepared batch already committed; re-prepare")
        if prepared.after is not None:
            base = prepared.after
            if base.committed_gen is None or base.committed_gen != self._gen:
                raise ValueError(
                    "document changed since prepare_batch; re-prepare the "
                    "batch")
        elif prepared.gen != self._gen:
            raise ValueError(
                "document changed since prepare_batch; re-prepare the batch")
        self.queue = prepared.queue_after
        applied: set = set()
        self._closure_memo.update(prepared.memo_overlay)
        try:
            for b, rows_arr, book, exec_plan in prepared.rounds:
                pairs, closures = book
                # bulk bookkeeping: closures were precomputed at prepare
                prev_clock = {a: self.clock.get(a) for a, _ in pairs}
                prev_deps = {p: self._all_deps.get(p) for p in pairs}
                self._all_deps.update(zip(pairs, closures))
                self.clock.update(pairs)
                if exec_plan is not None:
                    try:
                        self._execute_plan(b, exec_plan)
                    except BaseException:
                        self._rollback_bookkeeping((prev_clock, prev_deps))
                        raise
                applied.update(pairs)
        except BaseException:
            self.queue = [
                it for it in prepared.prior_queue
                if (it[0].actors[it[1]], int(it[0].seqs[it[1]])) not in applied]
            self._gen += 1  # queue changed: invalidate outstanding plans
            self._plan_failed()
            raise
        self._invalidate()
        # stamp AFTER the final invalidation: a chained follow-up plan
        # commits iff _gen still equals this value (nothing else mutated)
        prepared.committed_gen = self._gen
        # sever consumed state: the rounds' staged device buffers are
        # spent, and the base link's committed_gen check has passed — a
        # long pipelined session must not retain every plan (and its
        # device arrays) back to session start through the after-chain
        prepared.rounds = []
        prepared.after = None
        return self

    def _plan_failed(self):
        """Hook: a batch application raised after partial device work.
        Subclasses drop host caches that can no longer be trusted."""

    def _plan_shadow(self):
        raise NotImplementedError(
            f"{type(self).__name__} does not support two-phase ingestion")

    def _plan_round(self, b, mask, shadow):
        raise NotImplementedError

    def _execute_plan(self, b, exec_plan):
        raise NotImplementedError

    # ------------------------------------------------------------------
    # slow register path (host; matches oracle applyAssign semantics)
    # ------------------------------------------------------------------

    def _apply_slow(self, b, slots, kinds, values, actor_ranks, seqs,
                    slot_cap: int, reg_state):
        """Resolve non-fast assigns against register state.

        `reg_state` = (value, has, win_actor, win_seq, win_counter) numpy
        rows aligned with `slots` — pre-gathered by the ingest kernel's
        packed slow_info output, so resolution costs zero extra device
        round trips beyond the one write-back scatter."""
        wb = self._resolve_slow_host(b, slots, kinds, values, actor_ranks,
                                     seqs, slot_cap, reg_state)
        self._scatter_slow(wb)

    def _resolve_slow_host(self, b, slots, kinds, values, actor_ranks,
                           seqs, slot_cap: int, reg_state) -> np.ndarray:
        """HOST half of the slow register path: oracle-mirroring register
        resolution (winner = highest actor rank, survivors -> conflicts,
        `inc` folds into covered counters), mutating only host state
        (conflicts, value pool). Returns the packed (6, S) writeback
        matrix (ops/ingest.py WB_* row layout; padding rows carry
        `slot_cap`, the out-of-bounds drop sentinel). The device half is
        `_scatter_slow` on the solo path; the stacked executor
        (engine/stacked.py) re-pads every doc's matrix to a common width
        and writes them back as ONE vmapped scatter instead."""
        from ..ops.ingest import bucket

        slots = np.asarray(slots)
        kinds = np.asarray(kinds)
        values = np.asarray(values)
        actor_ranks = np.asarray(actor_ranks)
        seqs = np.asarray(seqs)
        g_v, g_h, g_wa, g_ws, g_wc = reg_state   # aligned per op
        uniq, inv, cnt = np.unique(
            slots, return_inverse=True, return_counts=True)
        S = bucket(len(uniq), 64)
        slots_p = np.full(S, slot_cap, np.int32)
        slots_p[: len(uniq)] = uniq
        # winner rows start cleared: a slot whose surviving-op list ends
        # empty (covered delete) writes back exactly these defaults
        w_v = np.zeros(S, np.int32)
        w_h = np.zeros(S, bool)
        w_wa = np.full(S, -1, np.int32)
        w_ws = np.zeros(S, np.int32)
        w_wc = np.zeros(S, bool)

        at = self.actor_table
        all_deps_by_key = self._all_deps

        # --- vectorized bulk path -------------------------------------
        # Realistic mixed loads are dominated by plain single-writer
        # SET/DEL on conflict-free slots (cfg5b: 1M bare deletes of
        # distinct base elements); resolving those through the per-op
        # Python loop below was a >10x cliff on the residual-heavy
        # benchmark. An op is "bulk" when: its slot carries exactly one
        # slow op this round (the device gate already guarantees no fast
        # op shares it), the slot holds no stored conflicts, the op is a
        # plain non-pooled SET or a DEL, and the op causally covers the
        # register's current single winner. Covered SET -> the op is the
        # new winner; covered DEL -> the register clears. Everything else
        # (concurrent writes, counters, pooled values, multi-op slots)
        # keeps the oracle-mirroring loop.
        single = cnt[inv] == 1
        if self.conflicts:
            conf_keys = np.fromiter(self.conflicts.keys(), np.int64,
                                    len(self.conflicts))
            no_conf = ~np.isin(slots.astype(np.int64), conf_keys)
        else:
            no_conf = np.ones(len(slots), bool)
        plain = (((kinds == KIND_SET) & (values >= 0))
                 | (kinds == KIND_DEL))
        bulk = single & no_conf & plain
        if bulk.any():
            exists = g_wa >= 0   # rank<0 (incl. empty) is always covered
            cov = np.ones(len(slots), bool)
            need = np.nonzero(bulk & exists)[0]
            if len(need):
                # ops of one change share one deps closure: sort the
                # needing ops by change, then vectorize the coverage
                # check per contiguous change group (per distinct
                # current-winner actor within it) — per-group cost is
                # proportional to group size, not to the whole round
                ckey = ((actor_ranks[need].astype(np.int64) << 32)
                        | seqs[need].astype(np.int64))
                order = np.argsort(ckey, kind="stable")
                nzo = need[order]
                cko = ckey[order]
                cuts = np.nonzero(np.diff(cko))[0] + 1
                starts = np.concatenate(([0], cuts))
                ends = np.concatenate((cuts, [len(cko)]))
                for s0, e0 in zip(starts, ends):
                    idx = nzo[s0:e0]
                    key = int(cko[s0])
                    rank, seq = key >> 32, key & 0xFFFFFFFF
                    deps = all_deps_by_key.get((at[rank], seq), {})
                    wran = g_wa[idx]
                    ur = np.unique(wran)
                    th = np.array([deps.get(at[int(r)], 0) for r in ur],
                                  np.int64)
                    cov[idx] = th[np.searchsorted(ur, wran)] >= g_ws[idx]
            bulk &= cov          # concurrent cases fall through to the loop
            j_set = np.nonzero(bulk & (kinds == KIND_SET))[0]
            i_set = inv[j_set]
            w_v[i_set] = values[j_set]
            w_h[i_set] = True
            w_wa[i_set] = actor_ranks[j_set]
            w_ws[i_set] = seqs[j_set]
            # covered DELs keep the cleared defaults; no stored conflicts
            # exist on bulk slots, so there is nothing to pop

        # --- oracle-mirroring loop for the rest -----------------------
        rest = np.nonzero(~bulk)[0]
        regs: dict = {}
        for j in rest:
            slot = int(slots[j])
            kind = int(kinds[j])
            value = int(values[j])
            actor_rank = int(actor_ranks[j])
            seq = int(seqs[j])
            actor_id = at[actor_rank]
            all_deps = all_deps_by_key.get((actor_id, seq), {})
            ops = regs.get(slot)
            if ops is None:
                # every slow op on a slot carries the same pre-round
                # register snapshot (gathered post fast-path writes)
                ops = []
                if g_h[j] or g_wa[j] >= 0:
                    ops.append({"actor_rank": int(g_wa[j]),
                                "seq": int(g_ws[j]),
                                "value": int(g_v[j]),
                                "counter": bool(g_wc[j])})
                ops.extend(self.conflicts.get(slot, []))
                regs[slot] = ops

            if kind == KIND_INC:
                for op in ops:
                    if op["counter"] and self._causally_covers(all_deps, op):
                        entry = self.value_pool[-op["value"] - 1]
                        self.value_pool.append(
                            {"value": entry["value"] + value,
                             "datatype": "counter"})
                        op["value"] = -len(self.value_pool)
                continue

            surviving = [op for op in ops
                         if not self._causally_covers(all_deps, op)]
            if kind == KIND_SET:
                pooled, counter = value, False
                if value < 0:
                    entry = b.value_pool[-value - 1]
                    self.value_pool.append(entry)
                    pooled = -len(self.value_pool)
                    counter = entry.get("datatype") == "counter"
                # at most one op per actor per register (same convergence
                # rule as the oracle, op_set.py _apply_assign: a later op
                # of the same change supersedes its predecessor; same-rank
                # pairs make the winner application-order-dependent)
                surviving = [o for o in surviving
                             if o["actor_rank"] != actor_rank]
                surviving.append({"actor_rank": actor_rank, "seq": seq,
                                  "value": pooled, "counter": counter})
            regs[slot] = surviving

        # finalize loop slots: winner = highest actor rank; extras become
        # conflicts (bulk slots were finalized vectorized above and never
        # share a slot with a loop op — the single-op gate)
        for s, slot_ops in regs.items():
            i = int(np.searchsorted(uniq, s))
            # descending by actor rank — unique per actor (the filter at
            # append time), so the order is total and
            # application-order-independent, matching the oracle
            # (backend/op_set.py _apply_assign)
            ops = sorted(slot_ops, key=lambda o: o["actor_rank"])[::-1]
            if ops:
                w = ops[0]
                w_v[i], w_h[i] = w["value"], True
                w_wa[i], w_ws[i], w_wc[i] = (w["actor_rank"], w["seq"],
                                             w["counter"])
            if ops[1:]:
                self.conflicts[s] = ops[1:]
            else:
                self.conflicts.pop(s, None)

        wb = np.zeros((6, S), np.int32)
        wb[0] = slots_p
        wb[1] = w_v
        wb[2] = w_h
        wb[3] = w_wa
        wb[4] = w_ws
        wb[5] = w_wc
        return wb

    def _scatter_slow(self, wb: np.ndarray):
        """DEVICE half of the slow register path: write the resolved
        winners back over the live register tables (one packed upload, or
        the legacy six-column comparator)."""
        import jax.numpy as jnp
        from ..ops.ingest import scatter_registers

        dev = self._dev
        regs_in = (dev["value"], dev["has_value"], dev["win_actor"],
                   dev["win_seq"], dev["win_counter"])
        self._count_dispatch(label="scatter_registers")
        self._count_h2d(wb.nbytes)   # the packed (6, S) writeback upload
        try:
            if self.packed_residual_writeback:
                # ONE packed h2d upload: with the packed slow_info fetch
                # this makes the whole residual register residue exactly
                # one d2h round trip + one upload (the WAN-tunnel shape
                # cfg5b bounds)
                from ..ops.ingest import (donation_enabled,
                                          scatter_registers_packed,
                                          scatter_registers_packed_donated)
                fn = (scatter_registers_packed_donated
                      if self.donate_buffers and donation_enabled()
                      else scatter_registers_packed)
                out = fn(*regs_in, jnp.asarray(wb))
            else:
                # legacy per-column upload (parity comparator): six
                # separate transfers, each paying per-transfer latency
                out = scatter_registers(
                    *regs_in, jnp.asarray(wb[0]), jnp.asarray(wb[1]),
                    jnp.asarray(wb[2].astype(bool)), jnp.asarray(wb[3]),
                    jnp.asarray(wb[4]),
                    jnp.asarray(wb[5].astype(bool)))
        except BaseException:
            # same donation invariant as the commit kernels (INTERNALS
            # §9.3): a raising donated writeback that CONSUMED the live
            # register tables leaves no valid device state — poison
            # loudly; a failure before consumption stays retryable
            from ..ops.ingest import buffers_consumed
            if self.donate_buffers and buffers_consumed(regs_in):
                self._device_lost = True
                self._dev = None
            raise
        dev["value"], dev["has_value"], dev["win_actor"], dev["win_seq"], \
            dev["win_counter"] = out
        self._invalidate()

    def _fetch_mirrors(self, keys) -> dict:
        """Host numpy mirrors of device tables, fetched as ONE packed
        transfer (RTT-bound on remote-attached chips). bool tables come
        back as bool; everything else int32."""
        from ..ops.ingest import pack_rows
        import jax.numpy as jnp
        dev = self._ensure_dev()
        self._count_dispatch(label="pack_rows")
        _tf = obs.now() if obs.ENABLED else 0
        packed = np.asarray(pack_rows(*(dev[k] for k in keys)))
        self._count_sync(label="mirror_fetch",       # the packed d2h fetch
                         dur_ns=(obs.now() - _tf) if _tf else 0,
                         d2h_bytes=packed.nbytes)
        out = {}
        for i, k in enumerate(keys):
            row = packed[i]
            out[k] = row.astype(bool) if dev[k].dtype == jnp.bool_ else row
        return out

    # ------------------------------------------------------------------
    # subclass hooks
    # ------------------------------------------------------------------

    def _ingest(self, batch, mask):
        raise NotImplementedError

    def _remap_device(self, remap: np.ndarray):
        raise NotImplementedError

    def _invalidate(self):
        self._host = None
        self._gen += 1

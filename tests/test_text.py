"""Text CRDT tests — coverage mirrors /root/reference/test/text_test.js:
editing, spans, control characters/embedded objects, concurrent edits.
"""

import automerge_tpu as am
from automerge_tpu import Text


def with_text(initial=""):
    return am.change(am.init("actor-1"), lambda d: d.__setitem__("text", Text(initial)))


class TestTextBasics:
    def test_empty_text(self):
        d = with_text()
        assert len(d["text"]) == 0
        assert str(d["text"]) == ""

    def test_initial_value(self):
        d = with_text("init")
        assert str(d["text"]) == "init"
        assert len(d["text"]) == 4
        assert d["text"][0] == "i"
        assert list(d["text"]) == ["i", "n", "i", "t"]

    def test_insert_at(self):
        d1 = with_text("it")
        d2 = am.change(d1, lambda d: d["text"].insert_at(1, "n", "i"))
        assert str(d2["text"]) == "init"

    def test_delete_at(self):
        d1 = with_text("initial")
        d2 = am.change(d1, lambda d: d["text"].delete_at(4, 3))
        assert str(d2["text"]) == "init"

    def test_set(self):
        d1 = with_text("cat")
        d2 = am.change(d1, lambda d: d["text"].set(1, "u"))
        assert str(d2["text"]) == "cut"

    def test_equality_with_str(self):
        d = with_text("abc")
        assert d["text"] == "abc"
        assert d["text"] == Text("abc")

    def test_immutable_outside_change(self):
        d = with_text("abc")
        try:
            d["text"].insert_at(0, "x")
            raised = False
        except TypeError:
            raised = True
        assert raised

    def test_elem_ids_stable(self):
        d = with_text("ab")
        e0 = d["text"].get_elem_id(0)
        d2 = am.change(d, lambda doc: doc["text"].insert_at(1, "x"))
        assert d2["text"].get_elem_id(0) == e0


class TestSpans:
    def test_to_spans_chars_only(self):
        d = with_text("hello")
        assert d["text"].to_spans() == ["hello"]

    def test_to_spans_with_embeds(self):
        d1 = with_text("ab")
        d2 = am.change(d1, lambda d: d["text"].insert_at(1, {"attribute": "bold"}))
        spans = d2["text"].to_spans()
        assert spans[0] == "a"
        assert am.to_json(spans[1]) == {"attribute": "bold"}
        assert spans[2] == "b"

    def test_to_string_skips_embeds(self):
        d1 = with_text("ab")
        d2 = am.change(d1, lambda d: d["text"].insert_at(1, {"x": 1}))
        assert str(d2["text"]) == "ab"

    def test_to_json(self):
        d = with_text("hi")
        assert am.to_json(d) == {"text": "hi"}


class TestConcurrentText:
    def test_concurrent_inserts_converge(self):
        base = with_text("helo")
        other = am.merge(am.init("actor-2"), base)
        a = am.change(base, lambda d: d["text"].insert_at(2, "l"))
        b = am.change(other, lambda d: d["text"].insert_at(4, "!"))
        m1 = am.merge(a, b)
        m2 = am.merge(b, a)
        assert str(m1["text"]) == str(m2["text"]) == "hello!"

    def test_concurrent_insert_same_position(self):
        base = with_text("--")
        other = am.merge(am.init("actor-2"), base)
        a = am.change(base, lambda d: d["text"].insert_at(1, "A"))
        b = am.change(other, lambda d: d["text"].insert_at(1, "B"))
        m1, m2 = am.merge(a, b), am.merge(b, a)
        assert str(m1["text"]) == str(m2["text"])
        assert str(m1["text"]) in ("-AB-", "-BA-")

    def test_insert_and_delete_converge(self):
        base = with_text("abcdef")
        other = am.merge(am.init("actor-2"), base)
        a = am.change(base, lambda d: d["text"].delete_at(1, 2))  # a___def -> adef
        b = am.change(other, lambda d: d["text"].insert_at(3, "X"))  # abcXdef
        m1, m2 = am.merge(a, b), am.merge(b, a)
        assert str(m1["text"]) == str(m2["text"]) == "aXdef"

    def test_save_load_round_trip(self):
        d1 = with_text("persist me")
        d2 = am.change(d1, lambda d: d["text"].delete_at(0, 8))
        loaded = am.load(am.save(d2), "actor-2")
        assert str(loaded["text"]) == "me"
        d3 = am.change(loaded, lambda d: d["text"].insert_at(0, "s", "a", "v", "e", " "))
        assert str(d3["text"]) == "save me"

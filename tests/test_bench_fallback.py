"""The driver-facing bench contract must survive a down tunnel.

Round 3's headline was lost to a single failed device probe at driver-run
time (BENCH_r03.json rc=3). bench.py now (a) retries the preflight with
backoff over a bounded budget and (b) falls back to the last locally
recorded on-chip run, explicitly marked stale. These tests pin that
contract by running bench.py as the driver does — a fresh subprocess —
with the probe budget forced tiny and the device made unreachable.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

import pytest

from automerge_tpu._env import virtual_cpu_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAST_GOOD = os.path.join(REPO, "BENCH_LAST_GOOD.json")


def _run_bench(env_extra):
    # make the probe fail REGARDLESS of tunnel health by landing the
    # subprocess on the scrubbed virtual-CPU platform via the ONE shared
    # scrub recipe (virtual_cpu_env: pops the axon plugin trigger AND pins
    # JAX_PLATFORMS=cpu — both are needed because a registered plugin can
    # initialize regardless of JAX_PLATFORMS, and the on-TPU test mode
    # skips conftest's own scrub). The strict probe rejects cpu exactly as
    # it rejects a dead tunnel. (An earlier version instead pointed the
    # plugin at an unroutable TEST-NET address, which stopped forcing
    # failure — and failed this test — precisely when the tunnel came UP.
    # Trade-off: the unroutable address also exercised preflight_device's
    # probe-hang/TimeoutExpired branch; the cpu probe exits fast, so that
    # branch is no longer covered here. AMTPU_PREFLIGHT_PROBE_S stays as a
    # belt-and-braces cap should the probe ever wedge.)
    env = virtual_cpu_env(1)
    # a lingering probe-skip knob (chip_session.sh exports it) would
    # bypass the very preflight these tests exercise
    env.pop("AMTPU_SKIP_PREFLIGHT", None)
    env.update({"AMTPU_PREFLIGHT_BUDGET_S": "1",
                "AMTPU_PREFLIGHT_PROBE_S": "15",
                **env_extra})
    return subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, env=env,
                          timeout=300, cwd=REPO)


@pytest.fixture()
def stash_last_good():
    """Preserve any real BENCH_LAST_GOOD.json around the test."""
    stash = None
    if os.path.exists(LAST_GOOD):
        fd, stash = tempfile.mkstemp(prefix="bench_last_good_stash_")
        os.close(fd)
        shutil.move(LAST_GOOD, stash)
    try:
        yield
    finally:
        if os.path.exists(LAST_GOOD):
            os.remove(LAST_GOOD)
        if stash:
            shutil.move(stash, LAST_GOOD)


def test_no_device_no_record_exits_3(stash_last_good):
    out = _run_bench({})
    assert out.returncode == 3, (out.stdout, out.stderr)
    assert "no last-good on-chip record" in out.stderr


def test_no_device_serves_stale_last_good(stash_last_good):
    # both "axon" (rounds 1-4 logs) and "tpu" (round-5 chip session) have
    # been observed as the chip's platform stamp — the rule everywhere is
    # `platform != "cpu"` (benchmarks.common.is_chip_platform), and the
    # fallback must serve a non-cpu record unchanged whichever string it
    # carries
    rec = {"metric": "ops_per_sec_merged_text_10k_actors_1M_doc",
           "value": 123, "unit": "ops/s", "vs_baseline": 0.001,
           "platform": "axon", "recorded_at_utc": "2026-07-30T00:00:00Z"}
    with open(LAST_GOOD, "w") as fh:
        json.dump(rec, fh)
    out = _run_bench({})
    assert out.returncode == 0, (out.stdout, out.stderr)
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["value"] == 123
    assert line["stale"] is True
    # best-of semantics stated as such, with provenance — NOT presented
    # as "the latest run" (ADVICE r5)
    assert "best verified on-chip run" in line["stale_reason"]
    assert "git_sha" in line["stale_reason"]


def test_corrupt_last_good_degrades_not_crashes(stash_last_good):
    """A truncated/corrupt BENCH_LAST_GOOD.json must behave exactly like
    a missing one (rc=3 refusing to hang), not crash the fallback
    (ADVICE r5)."""
    with open(LAST_GOOD, "w") as fh:
        fh.write('{"metric": "ops_per_sec_merged')     # torn mid-write
    out = _run_bench({})
    assert out.returncode == 3, (out.stdout, out.stderr)
    assert "unreadable" in out.stderr or "no last-good" in out.stderr


def test_preflight_hang_path(monkeypatch):
    """The probe-hang branch (a wedged tunnel makes the probe subprocess
    exceed its timeout) must honor the per-probe timeout override, retry
    within the budget, and come back False — this is the flow that keeps
    a dead tunnel from eating the driver's whole time budget (BENCH_r03
    was lost to exactly that). Covered in-process with a stubbed
    subprocess.run because no env trick can make the real probe hang
    deterministically (the old unroutable-address trick stopped hanging
    once the chip became reachable)."""
    import subprocess as sp

    from benchmarks import common

    monkeypatch.delenv("AMTPU_SKIP_PREFLIGHT", raising=False)
    monkeypatch.setenv("AMTPU_PREFLIGHT_PROBE_S", "5")
    seen_timeouts = []

    def hang(cmd, capture_output, text, timeout):
        seen_timeouts.append(timeout)
        raise sp.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(common.subprocess, "run", hang)
    monkeypatch.setattr(common.time, "sleep", lambda s: None)
    # budget small enough that the first failed probe exhausts it
    assert common.preflight_device(total_budget_s=0.5) is False
    assert seen_timeouts == [5.0]   # env override reached subprocess.run

    # malformed override: default per-probe timeout survives, no crash
    monkeypatch.setenv("AMTPU_PREFLIGHT_PROBE_S", "not-a-number")
    seen_timeouts.clear()
    assert common.preflight_device(timeout_s=90) is False
    assert seen_timeouts == [90.0]

    # a probe that succeeds after one hang: the retry loop must recover
    monkeypatch.setenv("AMTPU_PREFLIGHT_PROBE_S", "5")
    calls = {"n": 0}

    def hang_then_up(cmd, capture_output, text, timeout):
        calls["n"] += 1
        if calls["n"] == 1:
            raise sp.TimeoutExpired(cmd, timeout)
        return sp.CompletedProcess(cmd, 0, stdout="CHIP UP", stderr="")

    monkeypatch.setattr(common.subprocess, "run", hang_then_up)
    assert common.preflight_device(total_budget_s=60.0) is True
    assert calls["n"] == 2


def test_last_good_refresh_keeps_best_verified_run(tmp_path):
    """Tunnel weather varies run to run (78-115M ops/s observed in one
    night on an unchanged engine); the fallback must report the chip's
    demonstrated capability, so a slower later run must NOT downgrade
    the record, while a faster one replaces it and a cpu run never
    touches it. Every candidate here is first appended to the session
    log — promotion REQUIRES log presence (see the companion tests)."""
    import bench

    path = str(tmp_path / "last_good.json")
    log = str(tmp_path / "sessions.jsonl")
    n = [0]

    def mk(v, plat="tpu"):
        n[0] += 1
        rec = {"metric": "ops_per_sec_merged_text_10k_actors_1M_doc",
               "value": v, "unit": "ops/s", "platform": plat,
               "recorded_at_utc": f"2026-08-03T00:00:{n[0]:02d}Z"}
        bench.append_session_log(rec, log)     # the live-run discipline
        return rec

    refresh = lambda rec: bench.maybe_refresh_last_good(  # noqa: E731
        rec, path, session_log=log)
    assert refresh(mk(100))                               # first write
    assert not refresh(mk(80))                            # slower: kept
    assert json.load(open(path))["value"] == 100
    assert refresh(mk(120, "axon"))                       # faster
    assert json.load(open(path))["value"] == 120
    assert not refresh(mk(999, "cpu"))
    assert json.load(open(path))["value"] == 120
    # a prior record for a DIFFERENT metric is replaced, not compared
    with open(path, "w") as fh:
        json.dump({"metric": "other", "value": 10**9,
                   "platform": "tpu", "git_sha": "abc"}, fh)
    assert refresh(mk(120))
    assert json.load(open(path))["metric"] != "other"


def test_last_good_refresh_requires_session_log(tmp_path):
    """VERDICT r5 item 1b: a run whose JSON is not in the committed
    session log is REFUSED promotion (round 5's 115.5M flagship was
    exactly such an unlogged reading), and promotion re-stamps git_sha
    from the current checkout."""
    import bench

    path = str(tmp_path / "last_good.json")
    log = str(tmp_path / "sessions.jsonl")
    rec = {"metric": "ops_per_sec_merged_text_10k_actors_1M_doc",
           "value": 500, "unit": "ops/s", "platform": "tpu",
           "recorded_at_utc": "2026-08-03T01:00:00Z"}
    # not in the log (the log doesn't even exist): refused
    assert not bench.maybe_refresh_last_good(rec, path, session_log=log)
    assert not os.path.exists(path)
    # logged: promoted, with git_sha re-stamped at promotion time
    bench.append_session_log(rec, log)
    assert bench.maybe_refresh_last_good(rec, path, session_log=log)
    promoted = json.load(open(path))
    assert promoted["value"] == 500
    assert promoted.get("git_sha")          # stamped even though the
    assert "git_sha" not in rec             # candidate carried none
    # a corrupt log line must not wedge the gate for later valid lines
    with open(log, "a") as fh:
        fh.write('{"torn": ')
    rec2 = dict(rec, value=600, recorded_at_utc="2026-08-03T02:00:00Z")
    bench.append_session_log(rec2, log)
    assert bench.maybe_refresh_last_good(rec2, path, session_log=log)


def test_last_good_sha_less_prior_is_replaceable(tmp_path):
    """Satellite 1b demotion semantics: a prior record WITHOUT git_sha
    (or flagged unverified) predates the verification gate and must not
    defend its value — any verified run replaces it, even a slower one."""
    import bench

    path = str(tmp_path / "last_good.json")
    log = str(tmp_path / "sessions.jsonl")
    with open(path, "w") as fh:      # the round-5 shape: sha-less maximum
        json.dump({"metric": "ops_per_sec_merged_text_10k_actors_1M_doc",
                   "value": 115481761, "unit": "ops/s",
                   "platform": "tpu"}, fh)
    rec = {"metric": "ops_per_sec_merged_text_10k_actors_1M_doc",
           "value": 88_000_000, "unit": "ops/s", "platform": "tpu",
           "recorded_at_utc": "2026-08-03T03:00:00Z"}
    bench.append_session_log(rec, log)
    assert bench.maybe_refresh_last_good(rec, path, session_log=log)
    assert json.load(open(path))["value"] == 88_000_000


def test_committed_last_good_record_is_verified_shape():
    """The repo's live BENCH_LAST_GOOD.json must carry the post-demotion
    shape: a git_sha, and no unverifiable best-of maximum as its value
    (the demoted prior rides along as provenance instead)."""
    rec = json.load(open(LAST_GOOD))
    assert rec.get("git_sha"), "committed last-good record lost its git_sha"
    prior = rec.get("demoted_prior")
    if prior:
        assert rec["value"] != prior["value"]


def test_chip_platform_gate_accepts_axon():
    """Round 4's refresh gate (`platform == "tpu"`) dead-wired the
    last-good mechanism: the chip stamps "axon", so a successful on-chip
    run never refreshed the fallback (VERDICT r4 Weak #1). The gate must
    accept every non-cpu platform the device could report."""
    from benchmarks.common import is_chip_platform
    assert is_chip_platform("axon")   # this environment's chip
    assert is_chip_platform("tpu")    # a locally attached chip
    assert not is_chip_platform("cpu")


def test_mid_run_failure_serves_stale_last_good(tmp_path, monkeypatch, capsys):
    """A tunnel drop DURING measurement (not just at preflight) must also
    degrade to the stale-marked last-good record with the live error
    spelled out, instead of handing the driver a dead rc."""
    import bench
    from benchmarks import common

    path = str(tmp_path / "last_good.json")
    rec = {"metric": "ops_per_sec_merged_text_10k_actors_1M_doc",
           "value": 777, "unit": "ops/s", "platform": "tpu",
           "recorded_at_utc": "2026-07-31T01:04:54Z"}
    with open(path, "w") as fh:
        json.dump(rec, fh)
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", path)
    monkeypatch.setattr(common, "preflight_device",
                        lambda *a, **k: True)
    def boom():
        raise RuntimeError("tunnel RPC dropped mid-commit")
    monkeypatch.setattr(bench, "_measure", boom)

    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 777
    assert out["stale"] is True
    assert "tunnel RPC dropped mid-commit" in out["stale_reason"]

    # without a last-good record the failure must propagate (rc path)
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "absent"))
    with pytest.raises(RuntimeError):
        bench.main()


def test_prior_committed_value_newest_wins(tmp_path):
    """The cpu-floor baseline is the NEWEST committed record row for the
    (metric, platform) pair — by numeric round, so r100 outranks r99 —
    and torn lines / other platforms skip."""
    import benchmarks.common as C
    root = str(tmp_path)
    (tmp_path / "BENCH_CONFIGS_r01.json").write_text(
        json.dumps({"metric": "cfg5_x", "platform": "cpu",
                    "value": 100.0}) + "\n")
    (tmp_path / "BENCH_CONFIGS_r02.json").write_text(
        "not json\n"
        + json.dumps({"metric": "cfg5_x", "platform": "tpu",
                      "value": 999.0}) + "\n"
        + json.dumps({"metric": "cfg5_x", "platform": "cpu",
                      "value": 200.0}) + "\n")
    assert C.prior_committed_value("cfg5_x", "cpu", root=root) == 200.0
    assert C.prior_committed_value("cfg5_x", "tpu", root=root) == 999.0
    assert C.prior_committed_value("missing", "cpu", root=root) is None
    # numeric round ordering: lexicographically "r99" > "r100", but the
    # newest round must still win
    (tmp_path / "BENCH_CONFIGS_r99.json").write_text(
        json.dumps({"metric": "cfg5_x", "platform": "cpu",
                    "value": 300.0}) + "\n")
    (tmp_path / "BENCH_CONFIGS_r100.json").write_text(
        json.dumps({"metric": "cfg5_x", "platform": "cpu",
                    "value": 400.0}) + "\n")
    assert C.prior_committed_value("cfg5_x", "cpu", root=root) == 400.0


def test_headline_cpu_floor_machine_check(tmp_path, capsys):
    """cfg5/headline cpu rows carry a machine-checked floor against the
    latest committed cpu row: met -> threshold_met True; a regression
    records False AND prints loudly; chip rows are untouched (floor_met
    covers them); no committed prior seeds instead of checking."""
    import benchmarks.common as C
    root = str(tmp_path)
    (tmp_path / "BENCH_CONFIGS_r05.json").write_text(
        json.dumps({"metric": "cfg5_y", "platform": "cpu",
                    "value": 1000.0}) + "\n")

    ok = {"metric": "y", "value": 900.0, "unit": "ops/s",
          "platform": "cpu", "threshold": "base"}
    C.headline_cpu_floor(ok, "cfg5_y", root=root)
    assert ok["threshold_met"] is True
    assert "machine-checked" in ok["threshold"]

    bad = {"metric": "y", "value": 700.0, "unit": "ops/s",
           "platform": "cpu", "threshold": "base"}
    C.headline_cpu_floor(bad, "cfg5_y", root=root)
    assert bad["threshold_met"] is False
    assert "HEADLINE CPU FLOOR MISS" in capsys.readouterr().err

    chip = {"metric": "y", "value": 1.0, "unit": "ops/s",
            "platform": "axon", "threshold": "base"}
    C.headline_cpu_floor(chip, "cfg5_y", root=root)
    assert "threshold_met" not in chip

    fresh = {"metric": "z", "value": 1.0, "unit": "ops/s",
             "platform": "cpu", "threshold": "base"}
    C.headline_cpu_floor(fresh, "cfg5_z", root=root)
    assert "threshold_met" not in fresh and "seeds it" in fresh["threshold"]

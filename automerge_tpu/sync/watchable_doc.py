"""Single-document observable (counterpart of /root/reference/src/watchable_doc.js)."""

from __future__ import annotations

from ..backend import default as Backend
from .. import frontend as Frontend


class WatchableDoc:
    def __init__(self, doc):
        if doc is None:
            raise ValueError("doc argument is required")
        self._doc = doc
        self._handlers: list = []

    def get(self):
        return self._doc

    def set(self, doc):
        self._doc = doc
        for handler in list(self._handlers):
            handler(doc)

    def apply_changes(self, changes):
        old_state = Frontend.get_backend_state(self._doc)
        new_state, patch = Backend.apply_changes(old_state, changes)
        patch["state"] = new_state
        new_doc = Frontend.apply_patch(self._doc, patch)
        self.set(new_doc)
        return new_doc

    def register_handler(self, handler):
        if handler not in self._handlers:
            self._handlers.append(handler)

    def unregister_handler(self, handler):
        if handler in self._handlers:
            self._handlers.remove(handler)

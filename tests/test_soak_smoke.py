"""Short-budget smoke of the committed soak harness (scripts/soak.py).

The full campaign runs hundreds of seeds (round 4's ad-hoc version found
the net-zero-merge convergence bug); CI runs a handful per profile so the
harness itself can never rot. Reproduce any failure exactly with:
`python scripts/soak.py --profile <name> --sessions 1 --seed-base <seed>`.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import soak  # noqa: E402


@pytest.mark.parametrize("profile", sorted(soak.PROFILES))
def test_soak_profile_smoke(profile):
    for seed in range(3):
        soak.PROFILES[profile](seed)


def test_runner_reports_and_exits_cleanly():
    assert soak.run("general", sessions=2, seed_base=100) == 0


@pytest.mark.slow
def test_chaos_campaign_50_sessions():
    """The ISSUE-1 acceptance bar, runnable on demand (excluded from the
    tier-1 slice by the registered `slow` marker): 50 seeded 3-peer chaos
    sessions — drop/dup/reorder/delay plus one partition/heal cycle each —
    all converge byte-identically."""
    assert soak.run("chaos", sessions=50, seed_base=0) == 0

"""Device-residency tiering: millions of docs on bounded HBM.

The tier ladder (INTERNALS §22): hot docs live device-resident in shard
lanes; warm docs demote to host-side AMTPUCKPT1 checkpoint bundles
(`BundleStore`); cold bundles age to one spill file each on disk.
Demand paging rides sync traffic through `ShardedDocSet.deliver_round`,
admission hints (router park / quarantine release) prefetch, and
eviction is the learned working-set model of `policy.py` driven by the
same telemetry windows the rebalance policy reads.
"""

from .manager import ResidencyManager
from .policy import LruModel, ResidencyConfig, WorkingSetModel, make_model
from .store import BundleStore

__all__ = [
    "ResidencyManager",
    "ResidencyConfig",
    "BundleStore",
    "WorkingSetModel",
    "LruModel",
    "make_model",
]

"""One shard's execution lane: a device, its resident docs, and the
stacked commit programs that serve them.

A lane is the single-device unit of the sharded serving tier
(INTERNALS §15): every engine doc the placement table routes here lives
with its tables on THIS lane's device, and one ingest round across the
lane's touched docs executes through the PR-7 stacked multi-object
executor (`engine/stacked.py`) — admission, columnar planning, and the
round kernels are the SAME code the single-device path runs, so the
sharded and unsharded paths cannot drift; the lane only decides *where*
the programs run. A lane never talks to another lane's device: there is
no multi-device program on the commit path, hence no collective to even
audit (the doc-axis mesh audit in `shard/audit.py` proves the stronger
claim for the SPMD formulation).

Device pinning uses ``jax.default_device`` scoped to lane calls: every
`jnp.asarray`/`device_put` the engine performs inside a lane operation
lands on the lane's device. On a single-device host (the tier-1 test
environment) lanes share the one device and only the partitioning logic
is exercised — shard semantics never REQUIRE a device per lane.
"""

from __future__ import annotations

import contextlib

from .. import obs
from ..engine import stacked as _stacked
from ..engine.map_doc import DeviceMapDoc
from ..engine.text_doc import DeviceTextDoc

_DOC_KINDS = {"text": DeviceTextDoc, "map": DeviceMapDoc}


class ShardLane:
    """One device's shard: resident docs + stacked ingest."""

    def __init__(self, index: int, device=None, telemetry=None,
                 assert_budget: bool = True, doc_kind: str = "text",
                 capacity: int = 1024):
        self.index = index
        self.device = device
        self.docs: dict = {}          # doc_id -> engine doc
        self.doc_ops: dict = {}       # doc_id -> lifetime admitted wire ops
        self.telemetry = telemetry
        self.assert_budget = assert_budget
        self.doc_kind = doc_kind
        self.capacity = capacity
        self.stats = {"applies": 0, "stacked_applies": 0,
                      "per_object_applies": 0, "admitted_ops": 0,
                      "docs_in": 0, "docs_out": 0,
                      "cross_planned_docs": 0, "index_merges": 0}

    def stats_delta(self) -> dict:
        """A zeroed per-round counter delta (same keys as ``stats``) for
        the parallel executor's fold-at-the-barrier discipline."""
        return dict.fromkeys(self.stats, 0)

    def device_ctx(self):
        """Every engine call for this lane runs inside this context, so
        staged arrays and kernel launches land on the lane's device."""
        if self.device is None:
            return contextlib.nullcontext()
        import jax
        return jax.default_device(self.device)

    # -- population -----------------------------------------------------

    def ensure_doc(self, doc_id: str, kind: str = None,
                   capacity: int = None):
        """Materialize a doc on this lane (the lane's configured kind
        and slot capacity unless overridden — the ShardedDocSet threads
        its population-wide settings through the lane constructor)."""
        doc = self.docs.get(doc_id)
        if doc is None:
            with self.device_ctx():
                doc = _DOC_KINDS[kind or self.doc_kind](
                    doc_id, capacity=capacity or self.capacity)
            self.docs[doc_id] = doc
            self.doc_ops[doc_id] = 0
        return doc

    def adopt(self, doc_id: str, bundle: bytes):
        """Install a migrated doc from its checkpoint bundle (the
        restore stages the tables onto THIS lane's device)."""
        from ..checkpoint import restore_engine
        with self.device_ctx():
            doc = restore_engine(bundle)
        self.docs[doc_id] = doc
        self.doc_ops[doc_id] = 0
        self.stats["docs_in"] += 1
        # a promote boundary: the doc's tables just landed on this
        # device — feed its gauge and this lane's aggregate immediately
        # (the residency budget invariant reads the live gauges, not
        # the next commit)
        doc._note_footprint()
        self._note_footprint()
        return doc

    def export(self, doc_id: str) -> bytes:
        """Capture a resident doc as a checkpoint bundle and release it
        (the migration source half; commit-boundary only — the caller
        guarantees no in-flight plan)."""
        from ..checkpoint import capture_engine
        from ..obs import device_truth
        doc = self.docs[doc_id]
        with self.device_ctx():
            bundle = capture_engine(doc)
        del self.docs[doc_id]
        self.doc_ops.pop(doc_id, None)
        self.stats["docs_out"] += 1
        # a demote boundary: the tables leave the device with the doc —
        # retire its gauge (peak already recorded) and re-aggregate
        if device_truth.ENABLED:
            device_truth.REGISTRY.drop_footprint("doc", doc.obj_id)
        self._note_footprint()
        return bundle

    # -- the commit path ------------------------------------------------

    def ingest(self, deliveries: dict, stats: dict = None):
        """One serving round over this lane's touched docs:
        ``{doc_id: changes}`` (wire dicts or decoded columnar batches)
        executes as ONE stacked multi-object apply on the lane device
        (`engine/stacked.apply_stacked` — per-round budget asserted
        against the stats dict THIS apply returned, never the module
        global, so concurrent lanes assert race-free), falling back to
        the per-object engine exactly like the single-device backend
        when the population is ineligible. Returns the admitted wire-op
        count. `stats` redirects the per-round counter increments into
        a caller-owned delta dict — the parallel executor's per-worker
        fold discipline (INTERNALS §24): a worker accumulates into its
        task delta and the caller folds into ``self.stats`` at the
        round barrier, so no increment is ever lost to a concurrent
        writer."""
        if not deliveries:
            return 0
        st_out = self.stats if stats is None else stats
        items = [(self.ensure_doc(doc_id), changes)
                 for doc_id, changes in deliveries.items()]
        n_ops = sum(_stacked._item_ops(subs) for _, subs in items)
        _t0 = obs.now() if obs.ENABLED else 0
        with self.device_ctx():
            st = _stacked.apply_stacked(items)
            if st:
                st_out["stacked_applies"] += 1
                # cross-doc planning visibility (INTERNALS §16): how many
                # of this lane's doc-rounds rode a shared admission
                # template, and the bulk-merge count the budget bounds
                cd = st.get("cross_doc")
                if cd:
                    st_out["cross_planned_docs"] += cd.get(
                        "sched_shared", 0)
                st_out["index_merges"] += st.get("index_merges", 0)
                if self.assert_budget:
                    _stacked.assert_round_budget(st)
            else:
                for doc, changes in items:
                    if hasattr(changes, "n_changes"):
                        doc.apply_batch(changes)
                    else:
                        doc.apply_changes(changes)
                st_out["per_object_applies"] += 1
        st_out["applies"] += 1
        st_out["admitted_ops"] += n_ops
        for doc_id, changes in deliveries.items():
            self.doc_ops[doc_id] = (self.doc_ops.get(doc_id, 0)
                                    + _stacked._item_ops(changes))
        if self.telemetry is not None:
            # the per-shard admitted-ops window series the rebalance
            # policy reads (INTERNALS §15.3): one rolling counter per
            # lane, bounded cardinality regardless of population size
            self.telemetry.observe_count(
                "shard", f"lane{self.index}_admitted_ops", n_ops)
        if obs.ENABLED:
            obs.span("shard", "lane_ingest", _t0, args={
                "lane": self.index, "docs": len(items), "n_ops": n_ops,
                "stacked": bool(st)})
        # the stacked path commits outside the per-doc apply wrappers,
        # so feed each touched doc's footprint gauge here — the lane
        # ingest IS their commit boundary (the residency budget
        # invariant is asserted against the doc-kind peak gauge)
        for doc_id in deliveries:
            self.docs[doc_id]._note_footprint()
        self._note_footprint()
        return n_ops

    def device_footprint(self) -> dict:
        """Device-resident bytes of this lane: the sum of every resident
        doc's table footprint (dtype x shape; obs/device_truth.py,
        INTERNALS §19) — the per-shard-lane view the ``amtpu_device_``
        footprint gauges carry next to the per-doc ones."""
        per_doc = {doc_id: doc.device_footprint()["device_bytes"]
                   for doc_id, doc in self.docs.items()}
        return {"device_bytes": sum(per_doc.values()),
                "n_docs": len(per_doc), "per_doc": per_doc}

    def _note_footprint(self):
        from ..obs import device_truth
        if device_truth.ENABLED:
            device_truth.REGISTRY.note_footprint(
                "lane", f"lane{self.index}",
                self.device_footprint()["device_bytes"])

    def ring(self, doc_id: str, slots: int = None, donate: bool = False):
        """A K-deep pipelined ingestion ring (engine/pipeline) bound to
        this lane's device: the worker thread's chained prepares (host
        planning + h2d staging) and the caller's commits all run under
        the lane's device context — the streaming path for a shard's
        hot doc."""
        from ..engine.pipeline import PipelinedIngestor
        return PipelinedIngestor(self.ensure_doc(doc_id), slots=slots,
                                 donate=donate, device=self.device)

    def hottest_doc(self):
        """(doc_id, lifetime ops) of the lane's hottest resident doc, or
        None — the migration candidate the rebalance policy exports."""
        if not self.doc_ops:
            return None
        doc_id = max(self.doc_ops, key=self.doc_ops.get)
        return doc_id, self.doc_ops[doc_id]

    def texts(self) -> dict:
        """Materialize every resident text doc (outside the commit
        path; convergence checks and pulls)."""
        with self.device_ctx():
            return {doc_id: doc.text() for doc_id, doc in self.docs.items()
                    if isinstance(doc, DeviceTextDoc)}

"""Cross-doc columnar planning: one planning pass per lane per round.

The PR-5 columnar planner made per-BATCH planning state (change columns,
run detection, rank caches, descriptor templates) derive once per
immutable batch. The serving tiers broke that amortization back open: a
sharded lane or the multi-tenant tick delivers one SMALL batch PER DOC
per round, and every pure-function-of-batch fact — run detection over
the op columns, the dep-closure admission partition, packed head keys,
the (9, R) descriptor template — was re-derived per document even though
the whole touched population carries the SAME wire shape (cfg12's text
population: per-doc host planning floored the measurable asymmetry at
3.43x with no acceptance bar, docs/MEASUREMENTS.md).

This module amortizes host planning ACROSS the doc population the way
`engine/stacked.py` amortized dispatch:

- batches group by a content digest of their planning columns (op
  columns + per-change metadata; the obj id deliberately excluded — it
  names the target, it does not change the plan);
- per group, ONE shared `ColumnarChangeBatch` companion, ONE run
  detection (`runs.detect_runs` at base 0, rebased per doc by the
  existing `RoundPlan.rebase` contract), and ONE admission template per
  distinct clock projection (the only per-doc input admission reads) —
  instead of re-running `_schedule_columnar` + the detection walk per
  doc;
- the shared plan JOINS to per-doc state by vectorized rank lookup:
  one `np.searchsorted` of the group's actor table against each
  distinct doc interning table (rank order == lex order, so the doc
  table is presorted), seeding every doc's batch rank cache — packed
  head keys, parent prehashes, and the descriptor template included —
  in one pass per distinct interning shape.

Everything downstream is UNCHANGED: `_plan_round` consumes the seeded
caches through its existing fast paths, the bulk index merge and parent
resolution (genuinely per-doc state) stay per doc, and committed state
is byte-identical with the planner off — ``AMTPU_CROSS_DOC_PLAN=0``
keeps the per-doc planner verbatim as the parity comparator, composing
with ``AMTPU_COLUMNAR_PLAN`` exactly like the PR-5/PR-7 flags
(tests/test_columnar_plan.py, tests/test_stacked_rounds.py).

Consumed by `engine/stacked.apply_stacked` (and through it by
`shard/lane.ShardLane.ingest` and the service tick): INTERNALS §16.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from .. import obs
from . import learned_index
from .base import _GroupedRound, columnar_plan_enabled
from .runs import detect_runs
from .wire_columns import change_columns

__all__ = ["cross_doc_enabled", "preplan", "plan_signature"]


def cross_doc_enabled() -> bool:
    """Cross-doc planning is the default population path;
    ``AMTPU_CROSS_DOC_PLAN=0`` selects the per-doc parity comparator
    (read per call so tests can pin either path)."""
    return os.environ.get("AMTPU_CROSS_DOC_PLAN", "1") != "0"


def plan_signature(batch):
    """Content digest of a batch's PLANNING columns, cached on the batch.

    Covers everything admission + run planning read — per-change actors,
    seqs, dep contents, the batch actor table, and all seven op columns —
    and nothing they do not (obj id, messages). Two batches with equal
    signatures produce identical schedules and run partitions against
    equal doc state by construction. None = out of scope (pooled rich
    values, whose planning reads per-batch pool state)."""
    sig = getattr(batch, "_plan_sig", None)
    if sig is not None:
        return sig if sig != () else None
    if getattr(batch, "value_pool", None):
        try:
            batch._plan_sig = ()
        except AttributeError:
            pass
        return None
    h = hashlib.sha1()
    for col in (batch.op_kind, batch.op_target_actor, batch.op_target_ctr,
                batch.op_parent_actor, batch.op_parent_ctr, batch.op_value,
                batch.op_change, np.asarray(batch.seqs)):
        h.update(np.ascontiguousarray(col))
    h.update("\0".join(batch.actors).encode())
    h.update("\0".join(batch.actor_table).encode())
    for d in batch.deps:
        h.update(repr(sorted(d.items())).encode())
    sig = (batch.n_changes, batch.n_ops, h.digest())
    try:
        batch._plan_sig = sig
    except AttributeError:
        pass
    return sig


class _Group:
    """One planning group: docs whose batches carry identical planning
    columns this apply."""

    __slots__ = ("members", "cols", "run_plan", "sched", "row_table_idx",
                 "batch_table")

    def __init__(self):
        self.members = []        # [(doc, batch)]
        self.cols = None         # shared ColumnarChangeBatch companion
        self.run_plan = None     # (0, RoundPlan) full-batch detection
        self.sched = {}          # clock projection -> (rounds tmpl, queue)
        self.row_table_idx = None  # change row -> batch actor-table pos
        self.batch_table = None  # object ndarray of the batch actor table


class CrossDocPlan:
    """The shared planning state of one stacked apply (one lane round)."""

    def __init__(self):
        self.groups = []
        self._by_batch = {}      # id(batch) -> _Group
        self.stats = {"groups": 0, "docs": 0, "sched_shared": 0,
                      "sched_templated": 0, "detect_shared": 0,
                      "rank_seeded": 0}

    # -- admission -------------------------------------------------------

    def schedule(self, doc, batch):
        """The admission result for (doc, batch) — from the group's
        template when this clock projection was already scheduled, from
        one real `_schedule` run (which then seeds the template)
        otherwise. None = not in a group; caller falls back to
        `doc._schedule`."""
        g = self._by_batch.get(id(batch))
        if g is None or doc.queue:
            return None
        ckey = tuple(doc.clock.get(a, 0) for a in g.cols.local_actors)
        tmpl = g.sched.get(ckey)
        if tmpl is not None:
            self.stats["sched_shared"] += 1
            rounds = [_GroupedRound([(batch, rows, mask)])
                      for rows, mask in tmpl[0]]
            queue_after = [(batch, r) for r in tmpl[1]]
            return rounds, queue_after, []
        out = doc._schedule(batch)
        rounds, queue_after, _prior = out
        # template-ize: every round/queue item must reference THIS batch
        # alone (guaranteed with an empty prior queue; defensive check)
        t_rounds = []
        ok = True
        for r in rounds:
            groups = doc._group_round(r)
            if len(groups) != 1 or groups[0][0] is not batch:
                ok = False
                break
            t_rounds.append((groups[0][1], groups[0][2]))
        qrows = []
        if ok:
            for it in queue_after:
                if it[0] is not batch:
                    ok = False
                    break
                qrows.append(int(it[1]))
        if ok:
            g.sched[ckey] = (t_rounds, qrows)
            self.stats["sched_templated"] += 1
        return out

    # -- rank seeding (the vectorized per-doc join) ----------------------

    def seed_ranks(self):
        """Join the shared plans to per-doc state: one vectorized rank
        lookup (`np.searchsorted` over the doc's lex-sorted actor table)
        per DISTINCT interning shape per group, seeding every member
        doc's batch rank cache — packed head keys, parent prehashes and
        the descriptor template included — so `_plan_round` runs its
        cached fast path for the whole population. Must run AFTER actor
        interning covered every batch (the stacked apply's hoisted
        interning pass)."""
        from .text_doc import build_desc_template, run_head_fields
        from ..ops.ingest import bucket

        _t0 = obs.now() if obs.ENABLED else 0
        learned = learned_index.site_enabled("cross_doc_seed")
        for g in self.groups:
            _doc0, b0 = g.members[0]
            plan0 = g.run_plan[1] if g.run_plan is not None else None
            by_table = {}
            if learned:
                # learned seeding (engine/learned_index.py): (a) the
                # per-shape join goes through the packed actor-rank
                # model instead of the object-dtype searchsorted (one
                # model evaluation per distinct table, counted on the
                # "cross_doc_seed" site); (b) the O(table) content-tuple
                # build is memoized per (doc, interning generation) —
                # sound because every table mutation bumps the doc's
                # generation — so a large table is tuplized once per
                # intern epoch, not once per seeding pass; (c) member
                # docs of one (gen, shape) SHARE one cache-entry dict
                # with "gen" baked in (every rank-cache writer stores
                # shape-level values only: batch_rank/head fields/
                # desc_tmpl are pure functions of (op columns, interning
                # shape), so a late fill-in writes identical content).
                seeded = 0
                for doc, b in g.members:
                    table = doc.actor_table
                    if not table:
                        # every change of this doc's batch queued, so
                        # the interning hoist never saw it — no seed
                        continue
                    gen = doc._intern_gen
                    tk = getattr(doc, "_learned_tkey", None)
                    if tk is None or tk[0] != gen:
                        tk = (gen, tuple(table))
                        doc._learned_tkey = tk
                    ent = by_table.get(tk)
                    if ent is None:
                        got = learned_index.actor_positions(
                            table, g.batch_table, "cross_doc_seed")
                        if got is not None:
                            pos, okv = got
                            if not okv.all():
                                continue
                            batch_rank = pos.astype(np.int64)
                        else:
                            tbl = np.asarray(table, object)
                            pos = np.searchsorted(tbl, g.batch_table)
                            safe = np.clip(pos, 0, len(tbl) - 1)
                            if not (tbl[safe] == g.batch_table).all():
                                continue
                            batch_rank = pos.astype(np.int64)
                        ent = {"gen": gen,
                               "batch_rank": batch_rank,
                               "row_rank": batch_rank[g.row_table_idx]
                               .astype(np.int32)}
                        if plan0 is not None and plan0.n_runs:
                            ent.update(run_head_fields(
                                plan0, batch_rank, b0.op_target_actor,
                                b0.op_target_ctr, b0.op_parent_actor,
                                b0.op_parent_ctr))
                            R = bucket(plan0.n_runs, 64)
                            N = bucket(plan0.n_pairs, 256)
                            tmpl = build_desc_template(
                                plan0, b0.op_target_ctr, b0.op_change,
                                ent["head_rank"], ent["row_rank"],
                                np.asarray(b0.seqs, np.int32), R, N)
                            tmpl.setflags(write=False)
                            ent["desc_tmpl"] = tmpl
                        by_table[tk] = ent
                    g.cols.rank_cache[doc] = ent
                    seeded += 1
                self.stats["rank_seeded"] += seeded
                continue
            for doc, b in g.members:
                tkey = tuple(doc.actor_table)
                ent = by_table.get(tkey)
                if ent is None:
                    if not doc.actor_table:
                        # every change of this doc's batch queued, so the
                        # interning hoist never saw it — nothing to seed
                        continue
                    tbl = np.asarray(doc.actor_table, object)
                    pos = np.searchsorted(tbl, g.batch_table)
                    safe = np.clip(pos, 0, len(tbl) - 1)
                    if not (tbl[safe] == g.batch_table).all():
                        # an actor the hoist did not intern (defensive;
                        # unreachable post-hoist): skip this doc's seed,
                        # _plan_round resolves per doc as before
                        continue
                    batch_rank = pos.astype(np.int64)
                    ent = {"batch_rank": batch_rank,
                           "row_rank": batch_rank[g.row_table_idx]
                           .astype(np.int32)}
                    if plan0 is not None and plan0.n_runs:
                        ent.update(run_head_fields(
                            plan0, batch_rank, b0.op_target_actor,
                            b0.op_target_ctr, b0.op_parent_actor,
                            b0.op_parent_ctr))
                        R = bucket(plan0.n_runs, 64)
                        N = bucket(plan0.n_pairs, 256)
                        tmpl = build_desc_template(
                            plan0, b0.op_target_ctr, b0.op_change,
                            ent["head_rank"], ent["row_rank"],
                            np.asarray(b0.seqs, np.int32), R, N)
                        tmpl.setflags(write=False)
                        ent["desc_tmpl"] = tmpl
                    by_table[tkey] = ent
                g.cols.rank_cache[doc] = {"gen": doc._intern_gen, **ent}
                self.stats["rank_seeded"] += 1
        if obs.ENABLED:
            obs.span("plan", "rank_resolve", _t0, args={
                "what": "cross_doc_seed", **self.stats})


def preplan(decoded) -> CrossDocPlan:
    """Group one apply's decoded ``[(doc, batch), ...]`` population by
    planning-column content and derive each group's shared state (cols
    companion, full-batch run detection). Returns None when disabled or
    when no group reaches 2 members (the per-doc path is then exactly
    the legacy planner, untouched)."""
    if not cross_doc_enabled() or not columnar_plan_enabled():
        return None
    from .text_doc import DeviceTextDoc

    _t0 = obs.now() if obs.ENABLED else 0
    by_sig = {}
    for doc, batch in decoded:
        if not isinstance(doc, DeviceTextDoc):
            continue
        if doc.queue or not batch.n_changes or not batch.n_ops:
            continue
        sig = plan_signature(batch)
        if sig is None:
            continue
        by_sig.setdefault(sig, []).append((doc, batch))

    plan = CrossDocPlan()
    for sig, members in by_sig.items():
        if len(members) < 2:
            continue
        g = _Group()
        g.members = members
        _doc0, b0 = members[0]
        g.cols = change_columns(b0)
        g.batch_table = np.asarray(b0.actor_table, object)
        tpos = {a: i for i, a in enumerate(b0.actor_table)}
        g.row_table_idx = np.asarray([tpos[a] for a in b0.actors],
                                     np.int64)
        # ONE full-batch run detection per group (base 0; per-doc rebase
        # via the RoundPlan.rebase contract), reusing an existing cache
        # when the representative batch already detected
        rp = getattr(b0, "_run_plan_cache", None)
        if rp is not None and rp[1].n_ops == b0.n_ops:
            g.run_plan = (0, rp[1].rebase(-rp[0]))
        else:
            p0 = detect_runs(b0.op_kind, b0.op_target_actor,
                             b0.op_target_ctr, b0.op_parent_actor,
                             b0.op_parent_ctr, b0.op_value, b0.op_change,
                             0)
            for arr in (p0.hpos, p0.run_len, p0.head_slot, p0.rpos,
                        p0.res_new_slot, p0.blob):
                if isinstance(arr, np.ndarray):
                    arr.setflags(write=False)
            g.run_plan = (0, p0)
        for _doc, b in members:
            # shared companions: every member batch plans off ONE cols
            # object (mirror/pairs caches included) and ONE detection
            b._change_columns = g.cols
            if getattr(b, "_run_plan_cache", None) is None:
                b._run_plan_cache = g.run_plan
                plan.stats["detect_shared"] += 1
            plan._by_batch[id(b)] = g
        plan.groups.append(g)
    if not plan.groups:
        return None
    plan.stats["groups"] = len(plan.groups)
    plan.stats["docs"] = sum(len(g.members) for g in plan.groups)
    if obs.ENABLED:
        obs.span("plan", "cross_doc", _t0, args=dict(plan.stats))
    return plan

"""CI/bench SLO regression gate over the committed session log.

``BENCH_SESSIONS.jsonl`` is the append-only record of every headline run
(PR-4 credibility rules). This gate turns those rows into machine
checks: for each SLO, the NEWEST row of a (metric, platform) group is
compared against the PREVIOUS committed row of the same group — the
same cross-round, same-platform diffing the tracking-only methodology
prescribes, minus the human. Span-derived serial-profile terms
(prepare_s / commit_s, INTERNALS §11.4) and service SLOs (p99_tick_ms,
shed rate, replication lag at quiescence) are first-class fields.

Run modes:

- ``python -m benchmarks.slo_gate``: warn-only (ALWAYS exits 0) — the
  CI wiring; a regression prints loudly but cannot block a PR whose
  whole point may be a documented tradeoff.
- ``--strict``: exit 1 on any violation (pre-promotion checks).
- ``--sessions PATH``: an alternate session log (tests).

A group with only one committed row "seeds" its SLO (reported, never a
violation); a row missing an SLO field is reported as `missing` —
silent field rot is itself a regression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: Relative SLOs: (metric_prefix, dotted field, direction, slack).
#: direction "min": latest must be >= slack * prior (throughput-like);
#: direction "max": latest must be <= slack * prior (latency-like).
SLOS = [
    ("e2e_pipeline_ops_per_sec", "value", "min", 0.8),
    ("e2e_pipeline_ops_per_sec", "serial_profile.prepare_s", "max", 2.0),
    ("e2e_pipeline_ops_per_sec", "serial_profile.commit_s", "max", 2.5),
    ("ops_per_sec_merged_text", "value", "min", 0.8),
    ("cfg11_service", "value", "min", 0.7),
    ("cfg11_service", "p99_tick_ms", "max", 1.5),
    ("cfg11_service", "shed_rate", "max", 2.0),
    ("cfg12_sharded", "value", "min", 0.8),
    ("cfg12_sharded", "scaleup_vs_single_shard", "min", 0.9),
    # ISSUE 12: the text population graduates from tracking-only to an
    # enforced row — relative floor on its aggregate mesh throughput,
    # plus the cold-planning microbench's own throughput floor. The
    # scaleup RATIO gets an absolute bar below, not a relative one: its
    # denominator (the per-object single-shard leg) swings with box
    # conditions across sessions (docs/MEASUREMENTS.md ISSUE 12), so a
    # ratio-vs-prior rule would page on comparator weather
    ("cfg12_sharded", "text_population.aggregate_ops_per_sec",
     "min", 0.8),
    ("cfg12t_text_cold_prepare", "value", "min", 0.8),
    # ISSUE 13: binary-wire service rows — aggregate throughput floor
    # and a relative ceiling on wire bytes per admitted op (a format or
    # framing regression that bloats the wire shows up here even while
    # the absolute decode bars below still pass)
    ("cfg13_wire_service", "value", "min", 0.8),
    ("cfg13_wire_service", "wire_bytes_per_op", "max", 1.25),
    # ISSUE 14: lineage rows — feature-on throughput floor, plus a
    # relative ceiling on the sampled population's end-to-end
    # visibility p99 (a hop-site or tick regression that slows the
    # change's actual journey pages here even while throughput holds)
    ("cfg14_lineage", "value", "min", 0.8),
    ("cfg14_lineage", "visibility_p99_ms", "max", 1.5),
    # ISSUE 15: device-truth rows — throughput floor plus a relative
    # ceiling on staged bytes per admitted op (a staging regression that
    # re-uploads what donation kept resident, or fattens a packed
    # matrix, pages here even while throughput still holds)
    ("cfg15_device_truth", "value", "min", 0.8),
    ("cfg15_device_truth", "bytes_staged_per_op", "max", 1.25),
    # ISSUE 16: federation rows — replica-commit throughput floor plus
    # a relative ceiling on the cross-region visibility p99 (a link,
    # buffering, or handshake regression that slows a write's journey
    # across the WAN pages here even while local throughput holds)
    ("cfg16_federation", "value", "min", 0.8),
    ("cfg16_federation", "cross_region_visibility_p99_ms", "max", 1.5),
    # ISSUE 17: fused-round rows — throughput floor on the fused leg of
    # the megakernel A/B (the leg AMTPU_FUSED_ROUNDS ships on by
    # default; the XLA comparator leg is recorded alongside but carries
    # no bar of its own)
    ("cfg17_fused_rounds", "value", "min", 0.8),
    # ISSUE 18: residency rows — paged-serving throughput floor plus a
    # relative ceiling on the page-in p99 dwell (a restore-path or
    # staging regression that slows demand paging pages here even while
    # admitted throughput still holds; the budget bound itself is the
    # absolute rule below, never a relative one)
    ("cfg18_residency", "value", "min", 0.8),
    ("cfg18_residency", "page_in_p99_ms", "max", 1.5),
    # ISSUE 19: learned-index rows — throughput floor on the learned leg
    # of the host-planning A/B (the leg AMTPU_LEARNED_INDEX ships on by
    # default; the exact comparator leg is recorded alongside but
    # carries no bar of its own — the hard guarantees are the absolute
    # rules below)
    ("cfg19_learned_index", "value", "min", 0.8),
    # ISSUE 20: parallel-mesh rows — throughput floor on the parallel
    # leg of the lane-worker A/B (the leg AMTPU_PARALLEL_LANES ships on
    # by default on multi-lane meshes; the sequential comparator leg is
    # recorded alongside but carries no bar of its own — the speedup bar
    # is the gated absolute rule below)
    ("cfg20_parallel_mesh", "value", "min", 0.8),
]

#: Absolute SLOs: (metric_prefix, dotted field, op, bound) checked on
#: the newest row alone. The service bench quiesces before it records,
#: so ANY residual replication lag in its row is a wiring bug, not a
#: tradeoff.
ABS_SLOS = [
    ("cfg11_service", "max_lag_ops", "<=", 0),
    ("cfg11_service", "max_lag_ticks", "<=", 0),
    # the sharded commit path stays communication-free, forever: any
    # nonzero collective count in a committed cfg12 row is a regression
    # of the tier's core invariant, not a tunable
    ("cfg12_sharded", "collective_ops_total", "<=", 0),
    # the ISSUE-10 acceptance bar on the committed dryrun rows
    ("cfg12_sharded", "scaleup_vs_single_shard", ">=", 4.0),
    # the ISSUE-12 text bar: the row that used to carry "no bar"
    # (median-of-5 measured 2.27x with the planning floor lifted; bar
    # set with ~25% margin for the text mesh leg's rep spread)
    ("cfg12_sharded", "text_population.scaleup_vs_single_shard",
     ">=", 1.8),
    # the ISSUE-12 bulk-update budget on the committed cfg12t row: one
    # index merge per doc per round, never one sorted insert per range
    ("cfg12t_text_cold_prepare", "index_merges_per_doc_round", "<=", 1),
    # the ISSUE-13 acceptance bars on every committed cfg13 row,
    # forever: the service-ingest decode term stays >= 5x smaller than
    # the dict wire on the same seeded stream, and under 5% of the
    # tick budget (the "decode term ~vanishes" contract)
    ("cfg13_wire_service", "decode_speedup_vs_dict", ">=", 5.0),
    ("cfg13_wire_service", "decode_share_of_tick", "<=", 0.05),
    # the ISSUE-14 acceptance bars on every committed cfg14 row,
    # forever: sampled-mode overhead <= 5% vs the paired disabled leg,
    # and the disabled leg within 1% of its own paired disabled control
    # (the structural <=1% disabled-path claim is enforced by the timed
    # flag-check bound in tests/test_lineage.py; this guards the rows
    # against an off-path that starts doing work)
    ("cfg14_lineage", "overhead_pct", "<=", 5.0),
    ("cfg14_lineage", "off_ratio_vs_baseline", ">=", 0.99),
    # the ISSUE-15 acceptance bar on every committed cfg15 row, forever:
    # the steady-state stream compiles NOTHING inside its timed region —
    # a bucket-churn recompile is a structural regression of the
    # static-shape discipline, not box weather (also asserted in-run by
    # device_truth.steady_state)
    ("cfg15_device_truth", "recompiles_at_steady_state", "<=", 0),
    # the ISSUE-16 acceptance bar on every committed cfg16 row, forever:
    # the fabric quiesces before it records, so ANY residual
    # cross-region lag (pending group-token envelopes) in a committed
    # row is a wiring bug, not a tradeoff
    ("cfg16_federation", "residual_lag_tokens", "<=", 0),
    # the ISSUE-17 acceptance bars on every committed cfg17 row,
    # forever: the stacked round-loop dispatch count stays under the
    # TIGHTENED fused budget (APPLY_DISPATCH_BASE 8 + FUSED_PASS_
    # DISPATCH_BUDGET 4 per pass, engine/stacked.py — this workload is
    # single-pass, so 12 is the hard ceiling, not a tunable); the fused
    # leg's measured-vs-roofline ratio never regresses past the XLA
    # comparator's on the same stream (no-worse, with headroom for the
    # cpu sanity-band caveats of INTERNALS §19.4); and the fused entry
    # points compile NOTHING at steady state (also asserted in-run)
    ("cfg17_fused_rounds", "dispatch_per_round", "<=", 12.0),
    ("cfg17_fused_rounds", "roofline_ratio_vs_xla", "<=", 1.25),
    ("cfg17_fused_rounds", "recompiles_at_steady_state", "<=", 0),
    # the ISSUE-18 acceptance bar on every committed cfg18 row, forever:
    # the doc-kind peak footprint gauge never exceeds the device byte
    # budget — an ABSOLUTE bound, because "bounded HBM" is the tier's
    # whole contract (also asserted in-run at every rep boundary and
    # after the paged convergence reads); plus zero budget overruns from
    # the manager's own ledger
    ("cfg18_residency", "peak_over_budget", "<=", 1.0),
    ("cfg18_residency", "budget_overruns", "<=", 0),
    # the ISSUE-19 acceptance bars on every committed cfg19 row,
    # forever: the learned leg's plan/rank_resolve term, scaled to the
    # committed cfg12t 28672-plan shape, stays under 0.36 s (>= 2x
    # under the committed cfg12t 0.72 s term the tentpole exists to
    # kill), and the audit pass never catches a model returning a
    # wrong VERIFIED answer — exactness is the tier's whole contract,
    # so any nonzero count is a correctness regression, not a tunable
    # (both also asserted in-run before the row is emitted)
    ("cfg19_learned_index", "rank_resolve_s", "<=", 0.36),
    ("cfg19_learned_index", "model_wrong_answers", "<=", 0),
    # the ISSUE-20 acceptance bars on every committed cfg20 row,
    # forever: the parallel commit path stays communication-free (the
    # same zero-collective invariant as cfg12 — the workers change which
    # THREAD dispatches a lane's program, never which device it names),
    # compiles nothing at steady state on either leg, and beats the
    # paired sequential comparator >= 1.5x wherever the hardware can pay
    # it — the speedup field is DERIVED gated on the row's recorded
    # n_cores (lane workers are host threads; a sub-4-core box records
    # the honest ratio and the bar reads not-applicable, mirroring
    # cfg12's 8-device gating)
    ("cfg20_parallel_mesh", "collective_ops_total", "<=", 0),
    ("cfg20_parallel_mesh", "recompiles", "<=", 0),
    ("cfg20_parallel_mesh", "parallel_speedup_on_multicore", ">=", 1.5),
]

#: Derived fields computable from any row that carries the inputs.
DERIVED = {
    # sheds per admitted op: every committed cfg11 row carries both
    # inputs, so the gate can derive it even for pre-telemetry rows
    "shed_rate": lambda row: (
        row["shed_total"] / max(1, row["admitted_ops"])
        if "shed_total" in row and "admitted_ops" in row else None),
    # total cross-device collectives in the cfg12 commit-path HLO audit
    "collective_ops_total": lambda row: (
        sum(sum(v.values()) for v in row["collective_audit"].values())
        if isinstance(row.get("collective_audit"), dict) else None),
    # peak device footprint as a fraction of the cfg18 byte budget: the
    # gate recomputes the ratio from the row's own inputs so a stale or
    # hand-edited ratio field can never mask a breach
    "peak_over_budget": lambda row: (
        row["peak_footprint_bytes"] / row["budget_bytes"]
        if row.get("budget_bytes") and "peak_footprint_bytes" in row
        else None),
    # the cfg20 speedup bar, gated on the hardware that defines it:
    # lane workers are host threads, so the 1.5x bound only binds where
    # the row's own recorded core count can pay it (a sub-4-core row
    # reads "seeds"/not-applicable, never a free pass on a real mesh)
    "parallel_speedup_on_multicore": lambda row: (
        row["parallel_speedup_vs_sequential"]
        if row.get("n_cores", 0) >= 4
        and "parallel_speedup_vs_sequential" in row else None),
}


def _field(row: dict, dotted: str):
    if dotted in DERIVED:
        return DERIVED[dotted](row)
    cur = row
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def load_rows(path: str) -> list:
    """Measurement rows (metric + platform + numeric value) from one
    JSONL session log, file order preserved; non-row lines (the log's
    event entries, corrupt lines) are skipped."""
    rows = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and row.get("metric") \
                    and row.get("platform"):
                rows.append(row)
    return rows


def check(rows: list) -> list:
    """Evaluate every SLO; returns findings as dicts with `status` in
    {"ok", "violation", "seeds", "missing"} (violations first)."""
    groups: dict = {}
    for row in rows:
        groups.setdefault((row["metric"], row["platform"]), []).append(row)
    findings = []
    for (metric, platform), group in sorted(groups.items()):
        latest = group[-1]
        prior = group[-2] if len(group) > 1 else None
        for prefix, field, direction, slack in SLOS:
            if not metric.startswith(prefix):
                continue
            cur = _field(latest, field)
            base = dict(metric=metric, platform=platform, field=field,
                        slo=f"{direction} {slack}x prior")
            if cur is None:
                findings.append({**base, "status": "missing",
                                 "detail": "field absent in latest row"})
                continue
            ref = _field(prior, field) if prior else None
            if ref is None:
                findings.append({**base, "status": "seeds",
                                 "latest": cur,
                                 "detail": "no prior committed row"})
                continue
            if direction == "min":
                ok = cur >= slack * ref
            else:
                # a tiny prior makes any jitter a "regression": floor
                # the latency-like reference at a millisecond-scale
                # epsilon so 0 -> 0.1 ms does not page anyone
                ok = cur <= slack * max(ref, 1e-3)
            findings.append({**base,
                             "status": "ok" if ok else "violation",
                             "latest": cur, "prior": ref,
                             "bound": round(slack * max(
                                 ref, 1e-3 if direction == "max" else 0),
                                 6)})
        for prefix, field, op, bound in ABS_SLOS:
            if not metric.startswith(prefix):
                continue
            cur = _field(latest, field)
            base = dict(metric=metric, platform=platform, field=field,
                        slo=f"{op} {bound}")
            if cur is None:
                findings.append({**base, "status": "seeds",
                                 "detail": "field absent (pre-telemetry "
                                           "row)"})
                continue
            ok = cur <= bound if op == "<=" else cur >= bound
            findings.append({**base,
                             "status": "ok" if ok else "violation",
                             "latest": cur})
    order = {"violation": 0, "missing": 1, "seeds": 2, "ok": 3}
    findings.sort(key=lambda f: order[f["status"]])
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sessions", default=None,
                    help="session log path (default: repo "
                         "BENCH_SESSIONS.jsonl)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any violation (default: warn only)")
    args = ap.parse_args(argv)
    path = args.sessions or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_SESSIONS.jsonl")
    if not os.path.exists(path):
        print(f"slo_gate: no session log at {path} — nothing to check")
        return 0
    findings = check(load_rows(path))
    n_viol = sum(1 for f in findings if f["status"] == "violation")
    n_missing = sum(1 for f in findings if f["status"] == "missing")
    for f in findings:
        if f["status"] == "ok":
            continue
        tag = {"violation": "SLO VIOLATION", "missing": "SLO MISSING",
               "seeds": "SLO SEEDS"}[f["status"]]
        print(f"slo_gate: {tag}: {json.dumps(f, sort_keys=True)}",
              file=sys.stderr if f["status"] == "violation" else sys.stdout)
    print(f"slo_gate: {len(findings)} checks, {n_viol} violations, "
          f"{n_missing} missing "
          f"({'STRICT' if args.strict else 'warn-only'})")
    return 1 if args.strict and (n_viol or n_missing) else 0


if __name__ == "__main__":
    sys.exit(main())

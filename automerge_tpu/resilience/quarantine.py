"""Bounded parking lot for causally-premature changes.

A change whose dependencies the local document does not yet cover cannot be
applied; the backends queue such changes internally, but that queue is
unbounded — a misbehaving or malicious peer could grow it without limit by
streaming changes that reference deps it never sends. The inbound gate parks
premature changes here instead: bounded capacity, FIFO eviction, and
eviction statistics so operators can see loss happening (an evicted change
is gone until the transport layer re-requests or re-sends it — the
`ResilientChannel` retransmit path, or a peer reconnect).

Each parked change may carry a *sender* (the transport peer / service
tenant that delivered it). Capacity evictions then emit an attributed
``quar/evict_pressure`` obs event naming the tenant whose change was
lost — pressure loss is per-tenant observable, never silent — and a dead
peer's parked changes are reclaimable in one sweep (`drop_sender`, the
service tier's eviction path).
"""

from __future__ import annotations

from collections import OrderedDict

from .. import obs

#: Default per-document bound, sized for real reordering windows (a few
#: hundred in-flight changes on a lossy multi-path mesh). DocIds are
#: peer-chosen, so this alone is not the hostile-peer memory bound — the
#: inbound gate adds an aggregate cap across all docs
#: (``inbound.GLOBAL_CAPACITY``) with largest-queue-first eviction.
DEFAULT_CAPACITY = 1024


class QuarantineQueue:
    """FIFO of premature changes keyed ``(actor, seq)``, bounded."""

    __slots__ = ("capacity", "_items", "stats")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"quarantine capacity must be >= 1, "
                             f"got {capacity}")
        self.capacity = capacity
        # (actor, seq) -> (change, sender): attribution lives IN the
        # entry, so no second structure can drift out of sync with it
        self._items: OrderedDict = OrderedDict()
        self.stats = {"parked": 0, "evicted": 0, "released": 0, "peak": 0}

    def __len__(self) -> int:
        return len(self._items)

    def park(self, change: dict, requeue: bool = False, sender=None):
        """Admit one premature change; evicts the oldest entry on overflow.

        Returns the evicted change, or None. Re-parking the same
        ``(actor, seq)`` replaces the stored change in place (redelivered
        duplicates must not consume capacity). ``requeue`` marks a change
        coming back after an unsuccessful drain — it re-enters without
        counting as a fresh park in the stats. ``sender`` attributes the
        parked change to the transport peer that delivered it."""
        key = (change["actor"], change["seq"])
        if key in self._items:
            # replace in place; a sender-less redelivery keeps the
            # original attribution
            old_sender = self._items[key][1]
            self._items[key] = (change,
                                sender if sender is not None else old_sender)
            return None
        evicted = None
        if len(self._items) >= self.capacity:
            evicted = self._evict_oldest("capacity")
        self._items[key] = (change, sender)
        if not requeue:
            self.stats["parked"] += 1
            if obs.ENABLED:
                obs.event("quar", "park",
                          args={"actor": key[0], "seq": key[1]})
        if len(self._items) > self.stats["peak"]:
            self.stats["peak"] = len(self._items)
        return evicted

    def _evict_oldest(self, reason: str):
        ev_key, (evicted, ev_sender) = self._items.popitem(last=False)
        self.stats["evicted"] += 1
        if obs.ENABLED:
            obs.event("quar", "evict", args={"reason": reason})
            # the attributed pressure event: capacity loss names the
            # TENANT whose change was dropped, so an operator can see
            # which peer is losing data under storm, not just that
            # "something" was evicted
            obs.event("quar", "evict_pressure",
                      args={"tenant": ev_sender, "reason": reason,
                            "actor": ev_key[0], "seq": ev_key[1]})
        return evicted

    def drain_oldest(self):
        """Evict and return the single oldest entry (the inbound gate's
        aggregate-bound eviction), or None when empty."""
        if not self._items:
            return None
        return self._evict_oldest("aggregate")

    def drop_sender(self, sender) -> int:
        """Drop every parked change attributed to `sender` (dead-peer
        reclamation — the service eviction path). Returns the count; the
        drops count as evictions in the stats."""
        keys = [k for k, (_, s) in self._items.items() if s == sender]
        for key in keys:
            del self._items[key]
        self.stats["evicted"] += len(keys)
        return len(keys)

    def entries(self) -> list:
        """Non-destructive snapshot of the parked population:
        [(actor, seq, sender)] in admission order — the public face of
        ``_items`` for introspection (service reclamation checks, the
        postmortem dump)."""
        return [(a, s, sender)
                for (a, s), (_, sender) in list(self._items.items())]

    def drain_items(self) -> list:
        """Remove and return every parked ``(change, sender)`` pair in
        admission order. The caller re-parks whatever is still premature
        (passing the sender back through); ``released`` is credited by
        the inbound gate for drained changes that actually applied, so
        re-parking does not inflate it."""
        items = list(self._items.values())
        self._items.clear()
        return items

    def drain(self) -> list:
        """Remove and return every parked change (admission order)."""
        return [change for change, _ in self.drain_items()]

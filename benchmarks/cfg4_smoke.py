"""cfg4 stacked-rounds smoke: budget-asserted A/B + schema-valid trace.

Usage: python -m benchmarks.cfg4_smoke [--record-session]

The CI entry for the stacked multi-object tier (engine/stacked.py,
INTERNALS §12). One quick Trellis merge (the exact cfg4 generator,
benchmarks/run_all.trellis_changes) runs three ways:

1. AMTPU_STACKED_ROUNDS=1 — the stacked path, with the object-count-
   independent per-round dispatch budget ASSERTED
   (stacked.assert_round_budget) and the merge's dispatch count captured;
2. AMTPU_STACKED_ROUNDS=0 — the per-object comparator, same change set,
   committed state asserted identical (to_json + save), dispatch count
   captured for the A/B;
3. a traced stacked run: the plan/stack + commit/stacked_round spans and
   stacked kernel counters must export as schema-valid Chrome trace JSON
   (obs.export.validate_chrome_trace), so the new spans stay
   Perfetto-loadable.

`--record-session` appends the cpu A/B row to BENCH_SESSIONS.jsonl per
the PR-4 credibility rules (full JSON, git-sha-stamped, append-only).
On cpu the DISPATCH-COUNT delta is the headline — cpu e2e is
device-bound on the dev box and wall-clock A/Bs there are noise; the
wall-clock payoff lands where dispatch overhead is a real link
(docs/MEASUREMENTS.md cfg4 closure).
"""

import json
import os
import sys

os.environ.setdefault("AMTPU_SKIP_PREFLIGHT", "1")

from benchmarks.common import setup_jax_cache  # noqa: E402

setup_jax_cache()


def _merge(saved: bytes, changes, flag: str):
    """One measured merge against a FRESH core (each am.apply_changes on
    a shared base state would fork the prior run's advanced core by
    replay, polluting the A/B's dispatch counts with replay work)."""
    import time

    import automerge_tpu as am
    from automerge_tpu.engine import accounting, stacked

    os.environ["AMTPU_STACKED_ROUNDS"] = flag
    base = am.load(saved)
    stacked.LAST_STATS.clear()
    t0 = time.perf_counter()
    with accounting.track() as tr:
        merged = am.apply_changes(base, changes)
    dt = time.perf_counter() - t0
    return merged, {
        "dispatches": tr.thread_stats["dispatches"],
        "syncs": tr.thread_stats["syncs"],
        "merge_s": round(dt, 4),
        "stacked": dict(stacked.LAST_STATS),
    }


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    import automerge_tpu as am
    from automerge_tpu import obs
    from automerge_tpu.engine import stacked
    from automerge_tpu.obs.export import validate_chrome_trace
    from benchmarks.run_all import trellis_changes

    n_actors = 100
    base, changes, n_ops = trellis_changes(n_actors)
    saved = am.save(base)

    # warm-up both paths once (pays the one-time jit compiles so the
    # recorded wall clocks compare like for like; the dispatch COUNTS —
    # the cpu headline — are identical cold or warm)
    _merge(saved, changes, "1")
    _merge(saved, changes, "0")

    # 1. stacked path: parity + asserted budget
    m1, stat1 = _merge(saved, changes, "1")
    assert stat1["stacked"], "stacked path did not engage on cfg4 --quick"
    stacked.assert_round_budget(stat1["stacked"])

    # 2. per-object comparator: byte-identical committed state
    m0, stat0 = _merge(saved, changes, "0")
    assert not stat0["stacked"]
    canon = lambda d: json.dumps(am.to_json(d), sort_keys=True,  # noqa: E731
                                 default=str)
    assert canon(m1) == canon(m0), "stacked/per-object state diverged"
    assert am.save(m1) == am.save(m0)
    assert stat1["dispatches"] < stat0["dispatches"], (
        "stacked merge did not reduce dispatch count: "
        f"{stat1['dispatches']} vs {stat0['dispatches']}")

    # 3. traced stacked run, schema-validated
    os.environ["AMTPU_STACKED_ROUNDS"] = "1"
    trace_path = os.environ.get("AMTPU_TRACE_OUT", "cfg4_trace.json")
    fresh = am.load(saved)
    with obs.tracing():
        am.apply_changes(fresh, changes)
        rec = obs.recorder()
        names = {(r[obs.CAT], r[obs.NAME]) for r in rec.snapshot()}
        obs.write_trace(trace_path)
    assert ("plan", "stack") in names, "plan/stack span missing"
    assert ("commit", "stacked_round") in names, \
        "commit/stacked_round span missing"
    summary = validate_chrome_trace(trace_path)

    st = stat1["stacked"]
    row = {
        "metric": f"cfg4_stacked_dispatch_ab_{n_actors}_actors",
        "unit": "dispatches/merge",
        "value": stat1["dispatches"],
        "n_ops": n_ops,
        "dispatch_per_op": round(stat1["dispatches"] / n_ops, 4),
        "per_object_dispatches": stat0["dispatches"],
        "dispatch_reduction": round(
            stat0["dispatches"] / max(1, stat1["dispatches"]), 1),
        "stacked": st,
        "merge_s_stacked": stat1["merge_s"],
        "merge_s_per_object": stat0["merge_s"],
        "trace": summary,
        "threshold": ("asserted in code: stacked dispatches <= "
                      f"{stacked.APPLY_DISPATCH_BASE} + "
                      f"{stacked.PASS_DISPATCH_BUDGET} per round-pass "
                      "(>= 1 pass per causal round), "
                      "object-count-independent; state byte-identical "
                      "to the per-object comparator"),
    }
    from benchmarks.common import _platform
    row["platform"] = _platform()
    print(json.dumps(row), flush=True)

    if "--record-session" in argv:
        import datetime

        import bench as B
        row["recorded_at_utc"] = datetime.datetime.now(
            datetime.timezone.utc).isoformat()
        row["git_sha"] = B._git_sha()
        try:
            import subprocess
            dirty = subprocess.run(
                ["git", "status", "--porcelain"], capture_output=True,
                text=True, timeout=10).stdout.strip()
            if dirty:
                # honest provenance for rows recorded before the commit
                # that introduces the measured code (sha = parent)
                row["git_dirty"] = True
        except Exception:
            pass
        row["timed_region"] = (
            "one cfg4 --quick Trellis merge (100 actors, ~21 objects) "
            "through am.apply_changes; dispatches counted via "
            "engine/accounting thread totals; A/B = same change set, "
            "AMTPU_STACKED_ROUNDS 1 vs 0. On cpu the dispatch-count "
            "delta is the headline (e2e is device-bound on this box).")
        B.append_session_log(row)
        print(f"# appended to {B.SESSION_LOG_PATH}", file=sys.stderr)


if __name__ == "__main__":
    main()

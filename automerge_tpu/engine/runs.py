"""Typing-run detection over columnar op batches (host, vectorized numpy).

A *run* is an INS immediately followed by its SET, chained so each next INS
continues the previous element with a consecutive counter — the shape every
text editor produces. Runs are the engine's unit of bulk transfer: ~20-byte
descriptors + a value blob instead of 2 op rows per character
(ops/ingest.py:expand_runs*). Shared by the single-doc engine
(text_doc.DeviceTextDoc) and the vmapped doc-set engine
(doc_set.DeviceTextDocSet).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .._common import KIND_INS, KIND_SET


@dataclass
class RoundPlan:
    """Run/residual partition of one causally-ready round's op columns."""

    n_ops: int
    is_ins: np.ndarray       # bool[n_ops]
    n_ins: int
    new_slot: np.ndarray     # int64[n_ops] (0 where not ins)
    hpos: np.ndarray         # run-head op positions
    pair_pos: np.ndarray     # positions of all run INS ops (op order)
    run_len: np.ndarray      # int64[n_runs]
    rpos: np.ndarray         # residual op positions
    res_is_ins: np.ndarray   # bool over rpos

    @property
    def n_runs(self) -> int:
        return len(self.hpos)

    @property
    def n_pairs(self) -> int:
        return len(self.pair_pos)

    @property
    def n_res_ins(self) -> int:
        return int(self.res_is_ins.sum())


def detect_runs(kind, ta, tc, pa, pc, val64, op_row, base_elems: int
                ) -> RoundPlan:
    """Partition one round's op columns into runs and residual ops.

    `base_elems` is the document's live element count before this round;
    inserted elements take slots base_elems+1.. in op order."""
    n_ops = len(kind)
    is_ins = kind == KIND_INS
    n_ins = int(is_ins.sum())
    new_slot = np.where(is_ins, base_elems + np.cumsum(is_ins), 0)

    is_pair = np.zeros(n_ops, bool)
    if n_ops >= 2:
        is_pair[:-1] = ((kind[:-1] == KIND_INS) & (kind[1:] == KIND_SET)
                        & (op_row[1:] == op_row[:-1])
                        & (ta[1:] == ta[:-1]) & (tc[1:] == tc[:-1])
                        & (val64[1:] >= 0) & (val64[1:] < 2**31))
    cont = np.zeros(n_ops, bool)
    if n_ops >= 3:
        cont[2:] = (is_pair[2:] & is_pair[:-2]
                    & (op_row[2:] == op_row[:-2]) & (ta[2:] == ta[:-2])
                    & (tc[2:] == tc[:-2] + 1) & (pa[2:] == ta[:-2])
                    & (pc[2:] == tc[:-2]))
    run_head = is_pair & ~cont
    covered = np.zeros(n_ops, bool)
    covered[is_pair] = True
    covered[1:] |= is_pair[:-1]

    hpos = np.flatnonzero(run_head)
    pair_pos = np.flatnonzero(is_pair)
    if len(hpos):
        run_len = np.diff(np.append(
            np.searchsorted(pair_pos, hpos), len(pair_pos))).astype(np.int64)
    else:
        run_len = np.empty(0, np.int64)
    rpos = np.flatnonzero(~covered)
    res_is_ins = kind[rpos] == KIND_INS
    return RoundPlan(n_ops=n_ops, is_ins=is_ins, n_ins=n_ins,
                     new_slot=new_slot, hpos=hpos, pair_pos=pair_pos,
                     run_len=run_len, rpos=rpos, res_is_ins=res_is_ins)

"""Per-replication-group causal metadata for the federation tier.

Okapi's core economy argument, applied across regions: causal ordering
metadata should cost O(replication groups), not O(peers).  Inside a
region the sync tier already tracks per-peer clocks (the ClockMatrix) —
that stays intra-region.  BETWEEN regions, each room is one replication
group, and one monotone ordering token per (room, origin-region) is all
a receiver needs to order that group's cross-region shipments: the
token rides the ``AMTPUWIRE1`` manifest (``engine.wire_format``,
``group`` field) and mints ONCE per (doc, clock) encode group in
``SyncHub.flush`` — the same sharing discipline as the frame encode
itself, so N peer regions cost zero extra mints.

The per-change causal structure (deps hashes) still travels inside the
changes; the group token is the cheap ORDER observation — a receiver
learns "origin region R has shipped group token T for room X" in O(1)
without decoding the frame, which is what the cross-region lag gauges
and the heal-and-drain ladder read.
"""

from __future__ import annotations


class GroupClock:
    """One region's view of per-(room, origin-region) ordering tokens.

    - ``mint(room)`` — next outbound token for a room this region
      originates changes for.  Destination-independent: one mint serves
      every peer region of the group (O(groups), not O(peers)).
    - ``observe(room, origin, token)`` — max-merge an inbound token.
      Returns True when it ADVANCED the view (fresh information), False
      for duplicates/stale reorderings (the chaos tier duplicates and
      reorders freely; observation is idempotent).

    State is two flat dicts bounded by (rooms minted) + (room, origin)
    pairs observed — no per-peer, per-doc, or per-change growth.
    """

    __slots__ = ("region", "_heads", "_seen", "stats")

    def __init__(self, region: str):
        if not region or not isinstance(region, str):
            raise ValueError(f"region must be a non-empty string, "
                             f"got {region!r}")
        self.region = region
        self._heads: dict = {}   # room -> last minted token
        self._seen: dict = {}    # (room, origin) -> highest observed
        self.stats = {"minted": 0, "observed": 0, "stale": 0}

    def mint(self, room: str) -> list:
        """Next ordering token for `room`: the ``[origin, room, token]``
        triple the wire manifest carries (``validate_group_token``)."""
        tok = self._heads.get(room, 0) + 1
        self._heads[room] = tok
        self.stats["minted"] += 1
        return [self.region, room, tok]

    def observe(self, room: str, origin: str, token: int) -> bool:
        """Max-merge one inbound token; True iff it advanced the view."""
        key = (room, origin)
        if token > self._seen.get(key, 0):
            self._seen[key] = token
            self.stats["observed"] += 1
            return True
        self.stats["stale"] += 1
        return False

    def head(self, room: str) -> int:
        """This region's own mint head for a room (0 = never minted)."""
        return self._heads.get(room, 0)

    def seen(self, room: str, origin: str) -> int:
        """Highest token observed from `origin` for `room`."""
        return self._seen.get((room, origin), 0)

    def table(self) -> dict:
        """Dumpable view: ``{room: {origin: highest_token}}`` with this
        region's own mints under its own name — the describe() feed."""
        out: dict = {}
        for room, tok in self._heads.items():
            out.setdefault(room, {})[self.region] = tok
        for (room, origin), tok in self._seen.items():
            out.setdefault(room, {})[origin] = tok
        return out

# populated below — facade defined in facade.py, re-exported here at the end of the build step
from .facade import (  # noqa: F401
    BackendState, init, apply_changes, apply_local_change, get_patch,
    get_changes, get_changes_for_actor, get_missing_changes, get_missing_deps,
    merge, Backend,
)

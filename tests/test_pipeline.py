"""The pipelined ingestion seam (engine/pipeline + chained prepare):
equivalence with serial application, the overlap-never-loses invariant,
generation-checked aborts of background-planned batches, and bit-parity
of the sharded planning passes with their single-threaded forms."""

import time

import numpy as np
import pytest

import bench as B
from automerge_tpu.engine import (DeviceTextDoc, PipelinedIngestor,
                                  TextChangeBatch)
from automerge_tpu.engine import base as eb
from automerge_tpu.engine import runs as er

from test_prepare_commit import typing_change


def fresh_doc(n=4000):
    d = DeviceTextDoc("t")
    d.eager_materialize = True
    d.apply_batch(B.base_batch("t", n))
    d.text()
    return d


def halves(n=4000, k=3):
    return [B.merge_batch("t", 40, 30, n, seed=s + 1,
                          actor_prefix=f"p{s}")
            for s in range(k)]


def test_pipelined_matches_serial():
    """Pipelined ingestion produces byte-identical state to serial
    prepare/commit of the same batches."""
    hs = halves()
    serial = fresh_doc()
    for h in hs:
        serial.commit_prepared(serial.prepare_batch(h))
    piped = fresh_doc()
    with PipelinedIngestor(piped) as pipe:
        pipe.run(list(hs))
    assert piped.text() == serial.text()
    assert piped.elem_ids() == serial.elem_ids()
    assert piped.clock == serial.clock


def test_overlap_never_loses():
    """The in-process overlapped schedule must not lose to serial (the
    acceptance bound of ISSUE 2): byte-identical always; wall clock
    overlapped <= serial, with cfg5d's noise margin as the hard
    backstop on a contended one-core box."""
    n = 30_000
    hs = [B.merge_batch("t", 300, 200, n, seed=s, actor_prefix=p)
          for s, p in ((1, "alpha"), (2, "beta"))]
    expect = n + sum(h.n_ops for h in hs) // 2
    B.run_overlapped(hs, expect, obj_id="t", base_n=n)       # warm-up
    B.run_overlapped(hs, expect, obj_id="t", base_n=n, barrier=True)
    for attempt in range(3):
        ser = min(B.run_overlapped(hs, expect, obj_id="t", base_n=n,
                                   barrier=True) for _ in range(2))
        ov = min(B.run_overlapped(hs, expect, obj_id="t", base_n=n)
                 for _ in range(2))
        if ov <= ser:
            break
        time.sleep(2)            # escape a transient contention burst
    assert ov <= ser * 1.15, (
        f"overlapped {ov:.4f}s vs serial {ser:.4f}s")


def test_chained_prepare_matches_apply():
    """prepare(b2, after=p1) plans against p1's pending shadow and the
    pair commits to exactly the serial result."""
    hs = halves(k=2)
    direct = fresh_doc()
    direct.apply_batch(hs[0])
    direct.apply_batch(hs[1])
    doc = fresh_doc()
    p1 = doc.prepare_batch(hs[0])
    p2 = doc.prepare_batch(hs[1], after=p1)
    doc.commit_prepared(p1)
    doc.commit_prepared(p2)
    assert doc.text() == direct.text()
    assert doc.elem_ids() == direct.elem_ids()


def test_generation_mismatch_aborts_chained_plan():
    """A chained plan whose base committed but whose document then moved
    must abort with ValueError, document unharmed."""
    hs = halves(k=2)
    doc = fresh_doc()
    p1 = doc.prepare_batch(hs[0])
    p2 = doc.prepare_batch(hs[1], after=p1)
    doc.commit_prepared(p1)
    doc.apply_batch(B.merge_batch("t", 5, 10, 4000, seed=9,
                                  actor_prefix="zz"))   # outside mutation
    with pytest.raises(ValueError, match="re-prepare"):
        doc.commit_prepared(p2)
    # recovery: a fresh prepare commits fine
    doc.commit_prepared(doc.prepare_batch(hs[1]))


def test_commit_severs_chain_and_staged_buffers():
    """A committed plan drops its staged device buffers and its base
    link — a long pipelined session must not retain every plan (and its
    device arrays) back to session start (review finding)."""
    hs = halves(k=2)
    doc = fresh_doc()
    p1 = doc.prepare_batch(hs[0])
    p2 = doc.prepare_batch(hs[1], after=p1)
    doc.commit_prepared(p1)
    assert p1.rounds == [] and p1.after is None
    doc.commit_prepared(p2)
    assert p2.rounds == [] and p2.after is None


def test_chained_plan_requires_base_commit():
    """Committing a chained plan BEFORE its base is a ValueError."""
    hs = halves(k=2)
    doc = fresh_doc()
    p1 = doc.prepare_batch(hs[0])
    p2 = doc.prepare_batch(hs[1], after=p1)
    with pytest.raises(ValueError, match="re-prepare"):
        doc.commit_prepared(p2)
    doc.commit_prepared(p1)
    doc.commit_prepared(p2)


def test_pipeline_recovers_from_outside_mutation():
    """The documented degraded path: a mutation violating the pipeline
    contract costs a re-prepare, never corruption."""
    hs = halves(k=2)
    extra = B.merge_batch("t", 5, 10, 4000, seed=9, actor_prefix="zz")
    doc = fresh_doc()
    with PipelinedIngestor(doc) as pipe:
        pipe.feed(hs[0])
        pipe.commit_next()
        doc.apply_batch(extra)          # outside the pipeline
        pipe.feed(hs[1])
        pipe.flush()
    control = fresh_doc()
    control.apply_batch(hs[0])
    control.apply_batch(extra)
    control.apply_batch(hs[1])
    assert doc.text() == control.text()


def test_context_exit_flushes_fed_batches():
    """Exiting the context cleanly must COMMIT fed-but-unflushed batches,
    not silently drop them (apply_batch-equivalence contract) — and
    feeding PAST the slot bound self-drains instead of deadlocking on
    the exhausted semaphore (4 feeds into 2 slots, no explicit flush)."""
    hs = halves(k=4)
    doc = fresh_doc()
    with PipelinedIngestor(doc) as pipe:
        for h in hs:
            pipe.feed(h)           # no explicit flush, no drain calls
    control = fresh_doc()
    for h in hs:
        control.apply_batch(h)
    assert doc.text() == control.text()


def test_pipeline_rechains_after_fallback():
    """One outside mutation must not degrade the pipeline permanently:
    the worker drops the dead chain base and later batches chain again
    (bounded fallback count)."""
    hs = halves(k=5)
    extra = B.merge_batch("t", 5, 10, 4000, seed=9, actor_prefix="zz")
    doc = fresh_doc()
    with PipelinedIngestor(doc) as pipe:
        pipe.feed(hs[0])
        pipe.commit_next()
        doc.apply_batch(extra)          # the one violation
        for h in hs[1:]:
            pipe.feed(h)
            pipe.commit_next()
        n_fallbacks = pipe._fallbacks
    control = fresh_doc()
    control.apply_batch(hs[0])
    control.apply_batch(extra)
    for h in hs[1:]:
        control.apply_batch(h)
    assert doc.text() == control.text()
    assert n_fallbacks <= 2, (
        f"pipeline kept falling back ({n_fallbacks} times) instead of "
        "re-chaining")


def test_single_slot_pipeline_degrades_serial():
    """slots=1 must degrade to a serial schedule, not deadlock in
    run()'s drain loop (review finding: the drain threshold was
    hardcoded to 2)."""
    hs = halves(k=3)
    doc = fresh_doc()
    with PipelinedIngestor(doc, slots=1) as pipe:
        pipe.run(list(hs))
    control = fresh_doc()
    for h in hs:
        control.apply_batch(h)
    assert doc.text() == control.text()


def test_closed_pipeline_rejects_feed():
    """close() is terminal: feeding after it raises instead of
    restarting the joined worker thread."""
    doc = fresh_doc()
    pipe = PipelinedIngestor(doc)
    pipe.feed(halves(k=1)[0])
    pipe.flush()
    pipe.close()
    with pytest.raises(RuntimeError, match="closed"):
        pipe.feed(halves(k=1)[0])


def test_chained_prepare_refuses_remap():
    """Actors sorting below the existing table cannot chain (the remap
    would invalidate the pending base plan's staged ranks)."""
    doc = fresh_doc()
    p1 = doc.prepare_batch(B.merge_batch("t", 4, 10, 4000, seed=1,
                                         actor_prefix="m"))
    low = B.merge_batch("t", 4, 10, 4000, seed=2, actor_prefix="aa")
    with pytest.raises(ValueError, match="chain"):
        doc.prepare_batch(low, after=p1)
    doc.commit_prepared(p1)             # base plan still commits fine
    doc.apply_batch(low)


def test_k_deep_ring_matches_serial():
    """The K-deep ring (ISSUE 4 tentpole): 8 batches through 6 slots are
    byte-identical to serial application, with every batch after the
    first planned CHAINED on the worker (the ring genuinely pipelined,
    not silently degraded)."""
    hs = halves(k=8)
    serial = fresh_doc()
    for h in hs:
        serial.apply_batch(h)
    doc = fresh_doc()
    with PipelinedIngestor(doc, slots=6) as pipe:
        pipe.run(list(hs))
        st = pipe.stats
    assert doc.text() == serial.text()
    assert doc.elem_ids() == serial.elem_ids()
    assert doc.clock == serial.clock
    assert st["depth"] == 6 and st["committed"] == 8
    assert st["chained_prepares"] == 7, st
    assert st["serial_prepares"] == 0 and st["fallbacks"] == 0, st


def test_k_deep_overlap_never_loses():
    """Satellite: the K-deep schedule must not lose to serial across a
    LONGER stream than the classic two-half A/B (4 batches, depth 4) —
    same contention discipline as cfg5d."""
    n = 20_000
    hs = [B.merge_batch("t", 150, 200, n, seed=s, actor_prefix=f"s{s:02d}")
          for s in range(4)]
    expect = n + sum(h.n_ops for h in hs) // 2
    B.run_overlapped(hs, expect, obj_id="t", base_n=n)           # warm-up
    B.run_overlapped(hs, expect, obj_id="t", base_n=n, barrier=True)
    for attempt in range(3):
        ser = min(B.run_overlapped(hs, expect, obj_id="t", base_n=n,
                                   barrier=True) for _ in range(2))
        ov = min(B.run_overlapped(hs, expect, obj_id="t", base_n=n)
                 for _ in range(2))
        if ov <= ser:
            break
        time.sleep(2)
    assert ov <= ser * 1.15, (
        f"K-deep overlapped {ov:.4f}s vs serial {ser:.4f}s")


def test_gen_mismatch_abort_mid_ring():
    """Mid-ring abort: with a FULL ring of chained plans in flight, an
    outside mutation invalidates every pending plan; each affected
    commit degrades to a fresh inline prepare (never corruption) and
    the stream still lands byte-identical to the serial control."""
    hs = halves(k=6)
    extra = B.merge_batch("t", 5, 10, 4000, seed=9, actor_prefix="zz")
    doc = fresh_doc()
    with PipelinedIngestor(doc, slots=4) as pipe:
        for h in hs[:4]:
            pipe.feed(h)               # ring full: 4 plans speculated
        pipe.commit_next()
        doc.apply_batch(extra)         # mutation UNDER 3 pending plans
        for h in hs[4:]:
            pipe.feed(h)
        pipe.flush()
        st = pipe.stats
    assert st["fallbacks"] >= 1, st    # the degraded path genuinely ran
    control = fresh_doc()
    for h in hs[:4]:
        control.apply_batch(h)
    control.apply_batch(extra)
    for h in hs[4:]:
        control.apply_batch(h)
    # NOTE: commit order is ring order (batches 2-4 commit AFTER extra),
    # which matches the control's application order above
    assert doc.text() == control.text()
    assert doc.elem_ids() == control.elem_ids()


def test_donated_ring_parity_and_flag_restore(monkeypatch):
    """donate=True sessions run the *_donated commit kernels (forced on
    cpu via the donation gate) and land byte-identical state; close()
    restores the document's donate_buffers flag."""
    from automerge_tpu.ops import ingest as I
    monkeypatch.setattr(I, "_DONATION", True)      # force-enable on cpu
    hs = halves(k=5)
    serial = fresh_doc()
    for h in hs:
        serial.apply_batch(h)
    doc = fresh_doc()
    assert doc.donate_buffers is False
    with PipelinedIngestor(doc, slots=4, donate=True) as pipe:
        assert doc.donate_buffers is True
        pipe.run(list(hs))
    assert doc.donate_buffers is False             # restored on close
    assert doc.text() == serial.text()
    assert doc.elem_ids() == serial.elem_ids()


def test_donation_refuses_deferred_checkpoint_grab():
    """Donation invariant (INTERNALS §9): a donation-enabled doc refuses
    the checkpoint writer's zero-copy deferred grab (CaptureConflict ->
    the writer's commit-boundary sync path), while the inline grab —
    encoded before any further commit — still captures correctly."""
    import pytest as _pytest
    from automerge_tpu.checkpoint.engine_codec import (CaptureConflict,
                                                       grab)
    from automerge_tpu.checkpoint import writer as W

    doc = fresh_doc()
    doc.donate_buffers = True
    with _pytest.raises(CaptureConflict):
        grab(doc)
    g = grab(doc, inline=True)                     # the sync-path promise
    assert g["obj_id"] == "t"
    data = W.AsyncCheckpointer.capture(doc)        # inline end to end
    assert isinstance(data, bytes) and data


def causal_batch(n_actors=80):
    """Multi-round shape: seq-2 changes depending on the batch's own
    seq-1 changes, plus duplicates and an unsatisfiable straggler."""
    changes = []
    for a in range(n_actors):
        actor = f"ac{a:03d}"
        changes.append(typing_change(actor, 1, {"base": 1}, "xy",
                                     100, "base:5"))
        changes.append(typing_change(actor, 2, {}, "z", 200,
                                     f"{actor}:101"))
    changes.append(typing_change("ac000", 1, {"base": 1}, "xy", 100,
                                 "base:5"))          # duplicate row
    changes.append(typing_change("ghost", 3, {}, "g", 300, "ghost:299"))
    return TextChangeBatch.from_changes(changes, "t")


def seed_small():
    d = DeviceTextDoc("t")
    d.apply_changes([typing_change("base", 1, {}, "hello world", 1,
                                   "_head")])
    return d


def _round_rows(rounds):
    """Row partition of scheduled rounds, either representation (legacy
    (batch, row) tuple lists or columnar pre-grouped rounds)."""
    out = []
    for rnd in rounds:
        if isinstance(rnd, eb._GroupedRound):
            out.append([int(r) for _, rows, _ in rnd
                        for r in np.asarray(rows).tolist()])
        else:
            out.append([r for _, r in rnd])
    return out


def test_schedule_bulk_parity(monkeypatch):
    """The vectorized admission paths (columnar AND legacy bulk)
    partition EXACTLY like the per-change loop: same rounds, same row
    order, same queue."""
    batch = causal_batch()
    doc = seed_small()
    cols = doc._schedule(batch)                      # columnar (default)
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", "0")
    bulk = doc._schedule(batch)                      # n >= threshold: bulk
    monkeypatch.setattr(eb, "_BULK_SCHEDULE_MIN", 10**9)
    loop = doc._schedule(batch)                      # forced loop
    assert _round_rows(bulk[0]) == _round_rows(loop[0])
    assert _round_rows(cols[0]) == _round_rows(loop[0])
    assert [r for _, r in bulk[1]] == [r for _, r in loop[1]]
    assert [r for _, r in cols[1]] == [r for _, r in loop[1]]
    # and the applied documents agree end to end
    monkeypatch.delenv("AMTPU_COLUMNAR_PLAN", raising=False)
    d_cols = seed_small()
    d_cols.apply_batch(batch)
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", "0")
    d_bulk = seed_small()
    d_bulk.apply_batch(causal_batch())
    monkeypatch.setattr(eb, "_BULK_SCHEDULE_MIN", 10**9)
    d_loop = seed_small()
    d_loop.apply_batch(causal_batch())
    assert d_cols.text() == d_bulk.text() == d_loop.text()
    assert d_cols.clock == d_bulk.clock == d_loop.clock
    assert len(d_cols.queue) == len(d_bulk.queue) == len(d_loop.queue) == 1


def test_sharded_detect_runs_bit_identical(monkeypatch):
    """Sharded run detection concatenates to EXACTLY the single-shard
    partition on a mixed runs+residuals batch."""
    monkeypatch.setenv("AMTPU_PLAN_WORKERS", "3")
    monkeypatch.setattr(er, "_SHARD_MIN_OPS", 64)
    monkeypatch.setattr("automerge_tpu.engine.pipeline._POOL", None)
    batch = B.merge_batch("t", 50, 40, 1000, seed=5)
    # splice residuals (bare deletes) into some changes
    kind = batch.op_kind.copy()
    from automerge_tpu.engine.columnar import KIND_DEL
    kind[21::97] = KIND_DEL
    cols = (kind, batch.op_target_actor, batch.op_target_ctr,
            batch.op_parent_actor, batch.op_parent_ctr, batch.op_value,
            batch.op_change)
    sharded = er.detect_runs(*cols, 1000)
    single = er._detect_runs_single(*cols, 1000)
    for f in ("n_ops", "n_ins", "blob_lt_128", "blob_lt_256"):
        assert getattr(sharded, f) == getattr(single, f), f
    for f in ("hpos", "run_len", "head_slot", "rpos", "res_new_slot",
              "blob"):
        np.testing.assert_array_equal(getattr(sharded, f),
                                      getattr(single, f), err_msg=f)

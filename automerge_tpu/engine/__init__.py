from .columnar import MapChangeBatch, TextChangeBatch  # noqa: F401
from .doc_set import DeviceTextDocSet  # noqa: F401
from .map_doc import DeviceMapDoc  # noqa: F401
from .pipeline import PipelinedIngestor  # noqa: F401
from .text_doc import DeviceTextDoc  # noqa: F401
from .wire_columns import (ColumnarChangeBatch, change_columns,  # noqa: F401
                           decode_text_changes_columnar)

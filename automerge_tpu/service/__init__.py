"""Multi-tenant sync service tier (INTERNALS §13).

A tick-scheduled front end that multiplexes thousands of
``ResilientChannel`` tenant sessions over room-sharded ``SyncHub``s with
every resource explicitly bounded: per-tenant admission budgets enforced
as credit on the channel ack path, deadline-pressure shedding of the
lowest-priority work, a LIVE/SUSPECT/DEAD peer-health state machine whose
evictions reclaim hub + ClockMatrix + quarantine state, and snapshot-cache
join-storm coalescing for rejoins.

Quickstart (in-process transport; see README "Running the sync service"):

    from automerge_tpu.service import SyncService, ServiceConfig

    svc = SyncService(ServiceConfig(tick_budget_ms=5.0))
    svc.seed_doc("room-1", base_doc)
    sess = svc.connect("tenant-a", "room-1", send_raw=to_client_transport)
    ...                      # transport feeds frames to sess.on_wire
    svc.tick()               # one scheduler round (admission -> health
                             #  -> eviction -> one flush per room)
    print(svc.metrics())     # p99_tick_ms, shed_total, evictions, peaks,
                             #  max_lag_ops/ticks (INTERNALS §14.2)
    srv = svc.serve_metrics(port=9464)   # Prometheus /metrics + the
    print(svc.describe())    # black-box postmortem dump    # /describe
"""

from .budget import ServiceConfig, TenantBudget, approx_msg_bytes  # noqa: F401
from .server import DEAD, LIVE, SUSPECT, Room, SyncService, TenantSession  # noqa: F401,E501

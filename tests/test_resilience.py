"""Resilience layer: validation + quarantine + chaos transport + channel.

The contracts under test (ISSUE 1, docs/INTERNALS.md §7):

- every malformed-message fuzz case raises a typed ``ProtocolError`` (never
  ``KeyError``/``TypeError``) and leaves document state AND clock
  bit-identical — so a corrected redelivery is never silently skipped;
- causally-premature changes park in a BOUNDED quarantine with eviction
  stats and release automatically when their deps arrive;
- ``ChaosLink`` is deterministic in its seed; ``ResilientChannel`` restores
  lossless in-order exactly-once delivery over it;
- duplicate and reordered redelivery of the same change batch is idempotent
  at the hub layer on both backends (oracle and device).
"""

import copy
import json

import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from automerge_tpu.backend import device as device_backend
from automerge_tpu.backend import facade as oracle_backend
from automerge_tpu.resilience import (
    ChaosLink, PeerDeadError, ProtocolError, QuarantineQueue,
    ResilientChannel, validate_msg,
)
from automerge_tpu.resilience.inbound import inbound_gate
from automerge_tpu.sync import Connection, DocSet, SyncHub


def _mkdoc(key="x", value=1, actor="alice", backend=None):
    opts = {"actorId": actor}
    if backend is not None:
        opts["backend"] = backend
    doc = Frontend.init({"backend": am.Backend, **opts}) \
        if backend is None else Frontend.init(opts)
    return am.change(doc, lambda d: d.__setitem__(key, value))


def _fingerprint(doc_set, doc_id="doc"):
    """Bit-comparable snapshot of a doc's user state + clock."""
    doc = doc_set.get_doc(doc_id)
    if doc is None:
        return None
    state = Frontend.get_backend_state(doc)
    return (json.dumps(am.to_json(doc), sort_keys=True),
            json.dumps(dict(state.clock), sort_keys=True))


# ---------------------------------------------------------------------------
# wire-message fuzz: typed rejection, untouched state
# ---------------------------------------------------------------------------

GOOD_CHANGE = {"actor": "bob", "seq": 1, "deps": {},
               "ops": [{"action": "set", "obj": am.ROOT_ID,
                        "key": "y", "value": 2}]}

MALFORMED_MSGS = [
    "not a dict",
    None,
    {},                                           # missing docId
    {"docId": 7, "clock": {}},                    # docId wrong type
    {"docId": ""},                                # docId empty
    {"docId": "doc", "clock": "later"},           # clock wrong type
    {"docId": "doc", "clock": {3: 1}},            # clock key not an actor
    {"docId": "doc", "clock": {"a": "one"}},      # clock value not an int
    {"docId": "doc", "clock": {"a": -2}},         # negative seq
    {"docId": "doc", "changes": {"actor": "a"}},  # changes not an array
    {"docId": "doc", "changes": ["ch"]},          # change not an object
    {"docId": "doc", "changes": [{}]},            # change missing actor/seq
    {"docId": "doc", "changes": [{"actor": "a", "seq": 0, "deps": {},
                                  "ops": []}]},   # seq < 1
    {"docId": "doc", "changes": [{"actor": "a", "seq": "1", "deps": {},
                                  "ops": []}]},   # seq wrong type
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1,
                                  "ops": []}]},   # deps missing (strict)
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": [],
                                  "ops": []}]},   # deps wrong type
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1,
                                  "deps": {}}]},  # ops missing
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": ["op"]}]},          # op not a dict
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": [{"obj": "o"}]}]},  # action missing
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": [{"action": "frobnicate",
                                           "obj": "o", "key": "k"}]}]},
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": [{"action": "set",
                                           "key": "k", "value": 1}]}]},
    # truncated ops: assigns missing their payload / target
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": [{"action": "set",
                                           "obj": am.ROOT_ID,
                                           "key": "k"}]}]},
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": [{"action": "ins",
                                           "obj": "o", "key": "_head"}]}]},
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": [{"action": "inc",
                                           "obj": am.ROOT_ID, "key": "k",
                                           "value": "fast"}]}]},
    {"docId": "doc", "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": [{"action": "link",
                                           "obj": am.ROOT_ID, "key": "k",
                                           "value": 9}]}]},
]


class TestMalformedMessageFuzz:
    @pytest.mark.parametrize("msg", MALFORMED_MSGS,
                             ids=range(len(MALFORMED_MSGS)))
    def test_hub_rejects_typed_and_state_untouched(self, msg):
        ds = DocSet()
        ds.set_doc("doc", _mkdoc())
        hub = SyncHub(ds)
        handle = hub.add_peer("p", lambda m: None)
        hub.open()
        before = _fingerprint(ds)
        with pytest.raises(ProtocolError):
            handle.receive_msg(msg)
        assert _fingerprint(ds) == before   # doc + clock bit-identical

    @pytest.mark.parametrize("closed", [False, True])
    def test_connection_rejects_typed_both_lifecycles(self, closed):
        ds = DocSet()
        ds.set_doc("doc", _mkdoc())
        conn = Connection(ds, lambda m: None)
        conn.open()
        if closed:
            conn.close()
        before = _fingerprint(ds)
        for msg in ({"clock": {}},              # missing docId -> KeyError
                                                # before this layer existed
                    {"docId": "doc",
                     "changes": [{"actor": "a", "seq": 1, "deps": {},
                                  "ops": [{"action": "set",
                                           "obj": am.ROOT_ID,
                                           "key": "k"}]}]}):
            with pytest.raises(ProtocolError):
                conn.receive_msg(msg)
        assert _fingerprint(ds) == before

    def test_corrected_redelivery_applies_after_rejection(self):
        """The acceptance bit: a rejected delivery must not advance the
        clock, so the corrected redelivery of the same (actor, seq) is
        NOT skipped as a duplicate."""
        ds = DocSet()
        ds.set_doc("doc", _mkdoc())
        truncated = dict(GOOD_CHANGE,
                         ops=[{"action": "set", "obj": am.ROOT_ID,
                               "key": "y"}])
        with pytest.raises(ProtocolError):
            ds.deliver("doc", [truncated])
        ds.deliver("doc", [GOOD_CHANGE])
        assert am.to_json(ds.get_doc("doc")) == {"x": 1, "y": 2}

    def test_backend_apply_changes_raises_protocol_error(self):
        """Backend change application shares the validation layer: a
        structurally malformed change raises ProtocolError (a ValueError),
        never KeyError/TypeError, on both backends."""
        for make_state in (oracle_backend.init, device_backend.init):
            state = make_state()
            for bad in ([{"actor": "a"}],                 # no seq/ops
                        [{"actor": "a", "seq": 1, "deps": {},
                          "ops": [{"action": "set", "key": "k",
                                   "value": 1}]}],        # op missing obj
                        # deps-less changes are refused here too: lenient
                        # admission ships over the wire later, where
                        # strict peers would reject it — silent divergence
                        [{"actor": "a", "seq": 1, "ops": []}],
                        ["nope"], "nope", {"actor": "a"}):
                with pytest.raises(ProtocolError):
                    if make_state is oracle_backend.init:
                        oracle_backend.apply_changes(state, bad)
                    else:
                        device_backend.apply_changes(state, bad)

    def test_semantic_rejection_is_wrapped_at_the_gate(self):
        """A change that passes schema validation but fails mid-apply
        (unknown object) surfaces as ProtocolError through the wire path,
        and the backend's restore keeps state + clock bit-identical."""
        ds = DocSet()
        ds.set_doc("doc", _mkdoc())
        before = _fingerprint(ds)
        ghost = {"actor": "bob", "seq": 1, "deps": {},
                 "ops": [{"action": "set", "obj": "no-such-object",
                          "key": "k", "value": 1}]}
        with pytest.raises(ProtocolError):
            ds.deliver("doc", [ghost])
        assert _fingerprint(ds) == before


# ---------------------------------------------------------------------------
# quarantine: bounds, eviction stats, release
# ---------------------------------------------------------------------------

class TestQuarantine:
    def test_bounded_with_fifo_eviction_stats(self):
        q = QuarantineQueue(capacity=3)
        for seq in range(1, 6):
            q.park({"actor": "a", "seq": seq, "deps": {}, "ops": []})
        assert len(q) == 3
        assert q.stats["parked"] == 5
        assert q.stats["evicted"] == 2          # seqs 1 and 2 fell out
        assert q.stats["peak"] == 3
        assert [c["seq"] for c in q.drain()] == [3, 4, 5]

    def test_reparking_a_duplicate_does_not_consume_capacity(self):
        q = QuarantineQueue(capacity=2)
        c = {"actor": "a", "seq": 9, "deps": {}, "ops": []}
        q.park(c)
        q.park(dict(c))
        assert len(q) == 1 and q.stats["parked"] == 1

    def test_premature_changes_park_then_release_in_order(self):
        """Reordered wire delivery: seq 3 and 2 arrive before seq 1; both
        park (doc untouched), then one delivery of seq 1 releases the
        whole chain."""
        src = am.init("w")
        for i in range(3):
            src = am.change(src, lambda d, i=i: d.__setitem__(f"k{i}", i))
        c1, c2, c3 = am.get_all_changes(src)
        ds = DocSet()
        gate = inbound_gate(ds)
        ds.deliver("doc", [c3])
        ds.deliver("doc", [c2])
        assert ds.get_doc("doc") is None        # nothing applied yet
        assert gate.quarantined("doc") == 2
        ds.deliver("doc", [c1])
        assert gate.quarantined("doc") == 0
        assert am.to_json(ds.get_doc("doc")) == {"k0": 0, "k1": 1, "k2": 2}
        stats = gate.quarantine_stats("doc")
        assert stats["released"] == 2 and stats["parked"] == 2

    def test_poisoned_batch_does_not_lose_quarantined_changes(self):
        """Review regression: a batch that the backend rejects must put
        previously-quarantined changes BACK — their original delivery was
        already acked, so nothing upstream would re-send them."""
        src = am.init("w")
        src = am.change(src, lambda d: d.__setitem__("a", 1))
        src = am.change(src, lambda d: d.__setitem__("b", 2))
        c1, c2 = am.get_all_changes(src)
        ds = DocSet()
        gate = inbound_gate(ds)
        ds.deliver("doc", [c2])                 # parks, awaiting c1
        assert gate.quarantined("doc") == 1
        bad = {"actor": "z", "seq": 1, "deps": {},
               "ops": [{"action": "set", "obj": "no-such-object",
                        "key": "k", "value": 1}]}
        with pytest.raises(ProtocolError):
            ds.deliver("doc", [c1, bad])        # c2 drains into the batch
        # the poison is isolated: c1 AND the previously-parked c2 both
        # applied (salvage), only the bad change was rejected
        assert am.to_json(ds.get_doc("doc")) == {"a": 1, "b": 2}
        assert gate.quarantined("doc") == 0

    def test_cobatched_poison_does_not_drop_valid_changes(self):
        """Review regression: one message carrying [valid A, poison B].
        Transports ack on first delivery and the hub advances believed
        clocks on send, so A would never be re-sent — the gate must
        salvage A while rejecting B with the typed error."""
        src = am.init("w")
        src = am.change(src, lambda d: d.__setitem__("a", 1))
        (good,) = am.get_all_changes(src)
        poison = {"actor": "z", "seq": 1, "deps": {},
                  "ops": [{"action": "set", "obj": "no-such-object",
                           "key": "k", "value": 1}]}
        ds = DocSet()
        with pytest.raises(ProtocolError):
            ds.deliver("doc", [copy.deepcopy(good), poison])
        assert am.to_json(ds.get_doc("doc")) == {"a": 1}   # A survived
        # a change DEPENDING on the poison parks (premature), not lost
        dep = {"actor": "y", "seq": 1, "deps": {"z": 1},
               "ops": [{"action": "set", "obj": am.ROOT_ID,
                        "key": "d", "value": 4}]}
        with pytest.raises(ProtocolError):
            ds.deliver("doc", [copy.deepcopy(dep), copy.deepcopy(poison)])
        assert inbound_gate(ds).quarantined("doc") == 1

    def test_reentrant_delivery_is_not_stranded(self):
        """Review regression: a handler relaying a READY change for the
        same doc back into the gate mid-apply parks it re-entrantly; the
        outer drain must loop and apply it, not strand it."""
        src = am.init("w")
        src = am.change(src, lambda d: d.__setitem__("a", 1))
        src = am.change(src, lambda d: d.__setitem__("b", 2))
        c1, c2 = am.get_all_changes(src)
        ds = DocSet()
        relayed = []

        def relay(doc_id, doc):
            if not relayed:                     # once: relay c2 mid-apply
                relayed.append(True)
                ds.deliver(doc_id, [c2])

        ds.register_handler(relay)
        ds.deliver("doc", [c1])
        assert am.to_json(ds.get_doc("doc")) == {"a": 1, "b": 2}
        assert inbound_gate(ds).quarantined("doc") == 0

    def test_release_absorbs_remote_poison_without_crashing_local_path(self):
        """Review regression: a quarantined poison change becoming ready
        during a LOCAL set_doc must not raise out of the local caller —
        it is dropped, logged, and counted."""
        src = am.init("w")
        src = am.change(src, lambda d: d.__setitem__("a", 1))
        first = am.get_all_changes(src)
        ds = DocSet()
        ds.set_doc("doc", _mkdoc())
        conn = Connection(ds, lambda m: None)
        conn.open()
        poison = {"actor": "z", "seq": 1, "deps": {"w": 1},
                  "ops": [{"action": "set", "obj": "no-such-object",
                           "key": "k", "value": 1}]}
        conn.receive_msg({"docId": "doc", "clock": {"z": 1},
                          "changes": [poison]})      # premature: parks
        gate = inbound_gate(ds)
        assert gate.quarantined("doc") == 1
        # the local merge makes the poison ready; set_doc must SUCCEED
        local = am.apply_changes(ds.get_doc("doc"), first)
        ds.set_doc("doc", local)
        assert am.to_json(ds.get_doc("doc"))["a"] == 1
        assert gate.quarantined("doc") == 0          # dropped, not stuck
        assert gate.stats["parked_rejected"] == 1

    def test_aggregate_quarantine_bound_across_attacker_docids(self):
        """Review regression: docIds are peer-chosen, so the per-doc
        bound alone is no bound — the gate caps TOTAL parked changes
        across all docs and prunes emptied attacker-minted queues."""
        from automerge_tpu.resilience import inbound as inbound_mod

        ds = DocSet()
        gate = inbound_mod.InboundGate(ds, capacity=8, global_capacity=32)
        ds._inbound_gate = gate
        hub = SyncHub(ds)
        handle = hub.add_peer("evil", lambda m: None)
        hub.open()
        for i in range(200):                # fresh docId per premature change
            handle.receive_msg({"docId": f"doc-{i}", "clock": {"g": 2},
                                "changes": [{"actor": "g", "seq": 2,
                                             "deps": {}, "ops": []}]})
        assert gate._n_parked <= 32
        assert sum(gate.quarantined(f"doc-{i}") for i in range(200)) <= 32
        assert gate.stats["global_evicted"] >= 200 - 32
        # the tracking dict itself stays bounded too
        assert len(gate._quarantine) <= 32 + inbound_mod._MAX_IDLE_QUEUES

    def test_parked_poison_not_blamed_on_later_valid_sender(self):
        """Review regression: peer A's parked poison becoming ready must
        not raise out of peer B's perfectly valid delivery — it is
        dropped-and-logged, and B's changes apply."""
        src = am.change(am.init("w"), lambda d: d.__setitem__("a", 1))
        first = am.get_all_changes(src)
        poison = {"actor": "z", "seq": 1, "deps": {"w": 1},
                  "ops": [{"action": "set", "obj": "no-such-object",
                           "key": "k", "value": 1}]}
        ds = DocSet()
        gate = inbound_gate(ds)
        ds.deliver("doc", [poison])              # peer A: parks premature
        assert gate.quarantined("doc") == 1
        ds.deliver("doc", first)                 # peer B: valid, no raise
        assert am.to_json(ds.get_doc("doc")) == {"a": 1}
        assert gate.quarantined("doc") == 0
        assert gate.stats["parked_rejected"] == 1

    def test_handler_exception_is_not_reported_as_rejection(self):
        """Review regression: a user change handler raising AFTER the
        commit must propagate raw (the delivery applied) — wrapping it as
        a state-untouched ProtocolError would make the sender dedup the
        corrected redelivery of an already-applied change."""
        src = am.change(am.init("w"), lambda d: d.__setitem__("a", 1))
        (c1,) = am.get_all_changes(src)
        ds = DocSet()

        def angry(doc_id, doc):
            raise ValueError("handler blew up")

        ds.register_handler(angry)
        with pytest.raises(ValueError, match="handler blew up") as exc:
            ds.deliver("doc", [c1])
        assert not isinstance(exc.value, ProtocolError)
        assert am.to_json(ds.get_doc("doc")) == {"a": 1}   # committed

    def test_local_merge_releases_parked_changes(self):
        """Liveness without further network traffic: parked changes whose
        deps arrive via a LOCAL set_doc (e.g. an am.merge) release through
        the hub's doc_changed hook."""
        src = am.init("w")
        src = am.change(src, lambda d: d.__setitem__("a", 1))
        first = am.get_all_changes(src)
        src = am.change(src, lambda d: d.__setitem__("b", 2))
        second = [c for c in am.get_all_changes(src) if c["seq"] == 2]
        ds = DocSet()
        ds.set_doc("doc", _mkdoc())
        conn = Connection(ds, lambda m: None)
        conn.open()
        conn.receive_msg({"docId": "doc", "clock": {"w": 2},
                          "changes": second})
        assert am.to_json(ds.get_doc("doc")).get("b") is None  # parked
        local = am.apply_changes(ds.get_doc("doc"), first)
        ds.set_doc("doc", local)                # local merge supplies dep
        assert am.to_json(ds.get_doc("doc")) == {"x": 1, "a": 1, "b": 2}
        assert inbound_gate(ds).quarantined("doc") == 0


# ---------------------------------------------------------------------------
# chaos transport: determinism + fault injection
# ---------------------------------------------------------------------------

class TestChaosLink:
    def _trace(self, seed):
        got = []
        link = ChaosLink(got.append, seed=seed, drop=0.3, dup=0.25,
                         reorder=0.4, delay=0.3)
        for i in range(80):
            link.send({"n": i})
            if i % 3 == 0:
                link.pump()
        link.drain()
        return got, dict(link.stats)

    def test_deterministic_in_seed(self):
        t1, s1 = self._trace(42)
        t2, s2 = self._trace(42)
        t3, s3 = self._trace(43)
        assert t1 == t2 and s1 == s2
        assert t1 != t3                     # different seed, different fate

    def test_faults_actually_fire(self):
        _, stats = self._trace(7)
        assert stats["dropped"] > 0
        assert stats["duplicated"] > 0
        assert stats["reordered"] > 0
        assert stats["delayed"] > 0
        assert stats["delivered"] + stats["dropped"] \
            == stats["sent"] + stats["duplicated"]

    def test_partition_drops_in_flight_and_new_frames(self):
        got = []
        link = ChaosLink(got.append, seed=0)
        link.send({"n": 1})
        link.partition()
        link.send({"n": 2})
        link.drain()
        assert got == [] and link.stats["partition_dropped"] == 2
        link.heal()
        link.send({"n": 3})
        link.drain()
        assert got == [{"n": 3}]

    def test_codec_enforces_json_wire_format(self):
        link = ChaosLink(lambda m: None, seed=0)
        with pytest.raises(TypeError):
            link.send({"bad": {1, 2}})      # a set is not wire-JSON


# ---------------------------------------------------------------------------
# resilient channel: retry, dedup, ordering
# ---------------------------------------------------------------------------

def _duplex(seed, **faults):
    """Two channel endpoints over two directed chaos links."""
    parts = {}
    la = ChaosLink(lambda env: parts["b"].on_wire(env), seed=seed, **faults)
    lb = ChaosLink(lambda env: parts["a"].on_wire(env), seed=seed + 1,
                   **faults)
    got_a, got_b = [], []
    parts["a"] = ResilientChannel(la.send, got_a.append, seed=seed + 2)
    parts["b"] = ResilientChannel(lb.send, got_b.append, seed=seed + 3)
    return parts["a"], parts["b"], la, lb, got_a, got_b


class TestResilientChannel:
    def test_exactly_once_in_order_over_lossy_link(self):
        for seed in (1, 2, 3):
            a, b, la, lb, got_a, got_b = _duplex(
                seed, drop=0.35, dup=0.3, reorder=0.4, delay=0.3)
            for i in range(30):
                a.send({"n": i})
                if i % 2:
                    b.send({"m": i})
                la.pump()
                lb.pump()
                a.tick()
                b.tick()
            for _ in range(200):
                la.pump()
                lb.pump()
                a.tick()
                b.tick()
                if a.idle and b.idle and la.idle and lb.idle:
                    break
            assert got_b == [{"n": i} for i in range(30)], f"seed {seed}"
            assert got_a == [{"m": i} for i in range(30) if i % 2], \
                f"seed {seed}"
            assert a.idle and b.idle

    def test_retransmits_across_partition(self):
        a, b, la, lb, got_a, got_b = _duplex(5)
        la.partition()
        a.send({"n": 1})
        for _ in range(8):
            la.pump()
            lb.pump()
            a.tick()
            b.tick()
        assert got_b == [] and a.in_flight == 1
        la.heal()
        for _ in range(64):
            la.pump()
            lb.pump()
            a.tick()
            b.tick()
            if a.idle:
                break
        assert got_b == [{"n": 1}]
        assert a.stats["retransmits"] >= 1
        assert a.idle

    def test_raising_deliver_keeps_channel_consistent(self):
        """Review regression: a deliver callback that raises (the shipped
        wiring propagates ProtocolError from the sync layer) must not
        corrupt channel state — the ack still goes out, later payloads
        still release, and the error surfaces to the caller."""
        wire = []
        got = []

        def picky(payload):
            if payload.get("n") == 1:
                raise ProtocolError("rejected payload")
            got.append(payload)

        ch = ResilientChannel(wire.append, picky)
        with pytest.raises(ProtocolError):
            ch.on_wire({"kind": "data", "seq": 1, "ack": 0,
                        "payload": {"n": 1}})
        acks = [e for e in wire if e["kind"] == "ack"]
        assert acks and acks[-1]["ack"] == 1        # still acked
        # a retransmit of the rejected frame is a plain dup now
        ch.on_wire({"kind": "data", "seq": 1, "ack": 0, "payload": {"n": 1}})
        assert ch.stats["dup_dropped"] == 1
        # and the stream continues in order past the rejection
        ch.on_wire({"kind": "data", "seq": 2, "ack": 0, "payload": {"n": 2}})
        assert got == [{"n": 2}]
        assert ch.stats["deliver_errors"] == 1
        assert ch.idle

    def test_synchronous_loopback_retransmit_does_not_crash_tick(self):
        """Review regression: with a SYNCHRONOUS transport, a retransmit
        that fills the receiver's gap triggers an inline cumulative ack
        that mutates _unacked while tick() iterates it — must not
        KeyError."""
        parts = {}
        got = []
        drop_first = [True]

        def a_to_b(env):
            if env["kind"] == "data" and env["seq"] == 1 and drop_first[0]:
                drop_first[0] = False       # lose seq 1 exactly once
                return
            parts["b"].on_wire(env)

        parts["a"] = ResilientChannel(a_to_b, lambda m: None, seed=1)
        parts["b"] = ResilientChannel(
            lambda env: parts["a"].on_wire(env), got.append, seed=2)
        for i in range(1, 4):
            parts["a"].send({"n": i})       # 2, 3 buffer behind the gap
        for _ in range(8):                  # retransmit of 1 releases all
            parts["a"].tick()               # synchronously acking 1..3
            if parts["a"].idle:
                break
        assert got == [{"n": 1}, {"n": 2}, {"n": 3}]
        assert parts["a"].idle

    def test_receive_window_bounds_reorder_buffer(self):
        """Review regression: a peer streaming frames past an unfilled
        gap must not grow the reorder buffer without bound — frames
        beyond the window drop un-acked and redeliver later."""
        got = []
        ch = ResilientChannel(lambda e: None, got.append, recv_window=4)
        for seq in range(2, 50):          # withhold seq 1
            ch.on_wire({"kind": "data", "seq": seq, "ack": 0,
                        "payload": {"n": seq}})
        assert len(ch._recv_buf) <= 4
        assert ch.stats["window_dropped"] == 45     # seqs 5..49 dropped
        ch.on_wire({"kind": "data", "seq": 1, "ack": 0, "payload": {"n": 1}})
        assert got == [{"n": n} for n in range(1, 5)]   # window released

    def test_malformed_envelope_raises_protocol_error(self):
        ch = ResilientChannel(lambda e: None, lambda m: None)
        for env in ("x", {}, {"kind": "data", "seq": 1},           # no ack
                    {"kind": "data", "seq": 1, "ack": 0},          # no payload
                    {"kind": "warp", "seq": 1, "ack": 0},
                    {"kind": "data", "seq": "1", "ack": 0, "payload": {}}):
            with pytest.raises(ProtocolError):
                ch.on_wire(env)


class TestChannelRevive:
    """Reconnect epochs (ISSUE 16, INTERNALS §20.2): a channel declared
    dead by retransmit-cap exhaustion refuses send() until revive(),
    which starts a FRESH seq/ack epoch — stale pre-epoch data frames and
    stale acks from the old epoch must not corrupt the new one."""

    def test_dead_channel_refuses_send_until_revived(self):
        deaths = []
        ch = ResilientChannel(lambda env: None, lambda m: None,
                              max_retries=2, base_rto=1,
                              on_dead=deaths.append)
        ch.send({"n": 1})
        for _ in range(32):
            ch.tick()
            if ch.dead:
                break
        assert ch.dead and deaths == [ch]
        assert ch.in_flight == 0        # window reclaimed at death
        with pytest.raises(PeerDeadError):
            ch.send({"n": 2})
        ch.revive()
        assert not ch.dead and ch.epoch == 1
        assert ch.stats["revives"] == 1
        wire = []
        ch._send_raw = wire.append
        ch.send({"n": 2})
        # fresh epoch: seq numbering restarts at 1, envelope carries it
        assert wire[-1]["seq"] == 1 and wire[-1]["epoch"] == 1

    def test_stale_pre_epoch_frames_drop_unacked_after_revive(self):
        got, wire = [], []
        ch = ResilientChannel(wire.append, got.append)
        ch.on_wire({"kind": "data", "seq": 1, "ack": 0,
                    "payload": {"old": 1}})
        assert got == [{"old": 1}]
        ch.revive()                     # reconnect: receive state reset
        # a pre-epoch frame still floating in the network: same seq
        # space as the reset window, so it MUST drop un-acked — not
        # deliver, not dedup-by-seq against the new epoch
        n_acks = sum(1 for e in wire if e["kind"] == "ack")
        ch.on_wire({"kind": "data", "seq": 2, "ack": 0,
                    "payload": {"old": 2}})
        assert got == [{"old": 1}]
        assert ch.stats["stale_epoch_dropped"] == 1
        assert sum(1 for e in wire if e["kind"] == "ack") == n_acks
        # the new epoch's seq 1 delivers normally
        ch.on_wire({"kind": "data", "seq": 1, "ack": 0, "epoch": 1,
                    "payload": {"new": 1}})
        assert got == [{"old": 1}, {"new": 1}]

    def test_stale_acks_from_old_epoch_are_ignored(self):
        ch = ResilientChannel(lambda env: None, lambda m: None)
        ch.revive()                     # now sending in epoch 1
        ch.send({"n": 1})
        assert ch.in_flight == 1
        # an old-epoch ack (aepoch 0 != epoch 1) happens to cover seq 1:
        # it must NOT delete the new-epoch window entry
        ch.on_wire({"kind": "ack", "seq": 0, "ack": 1})
        assert ch.in_flight == 1
        assert ch.stats["stale_acks"] == 1
        ch.on_wire({"kind": "ack", "seq": 0, "ack": 1, "aepoch": 1})
        assert ch.in_flight == 0 and ch.idle

    def test_coordinated_revive_recovers_duplex_after_death(self):
        parts = {}
        la = ChaosLink(lambda env: parts["b"].on_wire(env), seed=11)
        lb = ChaosLink(lambda env: parts["a"].on_wire(env), seed=12)
        got_b = []
        parts["a"] = a = ResilientChannel(la.send, lambda m: None,
                                          seed=13, max_retries=3,
                                          base_rto=1, max_rto=2)
        parts["b"] = b = ResilientChannel(lb.send, got_b.append, seed=14)
        la.partition()
        a.send({"n": 1})
        dead = False
        for _ in range(256):
            la.pump()
            lb.pump()
            try:
                a.tick()
            except PeerDeadError:
                dead = True
                break
            b.tick()
        assert dead and a.dead
        la.heal()
        a.revive()
        b.revive()                      # both ends: the hello handshake
        a.send({"n": 1})                # upper layer re-sends (window
        a.send({"n": 2})                # was reclaimed at death)
        for _ in range(128):
            la.pump()
            lb.pump()
            a.tick()
            b.tick()
            if a.idle and b.idle and la.idle and lb.idle:
                break
        assert got_b == [{"n": 1}, {"n": 2}]
        assert a.idle and b.idle
        assert a.epoch == 1 and b._peer_epoch == 1


# ---------------------------------------------------------------------------
# hub idempotency under duplicate + reordered redelivery (both backends)
# ---------------------------------------------------------------------------

def _backend_doc(kind, actor):
    if kind == "oracle":
        return Frontend.init({"actorId": actor,
                              "backend": oracle_backend.Backend})
    return Frontend.init({"actorId": actor,
                          "backend": device_backend.DeviceBackend})


@pytest.mark.parametrize("kind", ["oracle", "device"])
class TestHubRedeliveryIdempotency:
    def _hub_with_doc(self, kind):
        ds = DocSet()
        ds.set_doc("doc", _backend_doc(kind, "h"))
        hub = SyncHub(ds)
        box = []
        handle = hub.add_peer("p", box.append)
        hub.open()
        return ds, hub, handle, box

    def _batches(self, kind):
        src = _backend_doc(kind, "w")
        src = am.change(src, lambda d: d.__setitem__("a", 1))
        b1 = am.get_all_changes(src)
        src = am.change(src, lambda d: d.__setitem__("b", 2))
        b2 = [c for c in am.get_all_changes(src) if c["seq"] == 2]
        return b1, b2

    def test_duplicate_batch_is_idempotent(self, kind):
        ds, hub, handle, _ = self._hub_with_doc(kind)
        b1, _ = self._batches(kind)
        msg = {"docId": "doc", "clock": {"w": 1}, "changes": b1}
        handle.receive_msg(copy.deepcopy(msg))
        first = _fingerprint(ds)
        assert json.loads(first[1]) == {"w": 1}
        for _ in range(3):                  # exact redeliveries: no-ops
            handle.receive_msg(copy.deepcopy(msg))
        assert _fingerprint(ds) == first

    def test_reordered_batches_converge(self, kind):
        ds, hub, handle, _ = self._hub_with_doc(kind)
        b1, b2 = self._batches(kind)
        handle.receive_msg({"docId": "doc", "clock": {"w": 2},
                            "changes": copy.deepcopy(b2)})
        assert "b" not in am.to_json(ds.get_doc("doc"))   # parked, not lost
        handle.receive_msg({"docId": "doc", "clock": {"w": 2},
                            "changes": copy.deepcopy(b1)})
        snap = am.to_json(ds.get_doc("doc"))
        assert snap["a"] == 1 and snap["b"] == 2
        # and a duplicate of the ALREADY-parked-then-applied batch is inert
        final = _fingerprint(ds)
        handle.receive_msg({"docId": "doc", "clock": {"w": 2},
                            "changes": copy.deepcopy(b2)})
        assert _fingerprint(ds) == final

    def test_inconsistent_seq_reuse_is_protocol_error(self, kind):
        ds, hub, handle, _ = self._hub_with_doc(kind)
        b1, _ = self._batches(kind)
        handle.receive_msg({"docId": "doc", "clock": {"w": 1},
                            "changes": copy.deepcopy(b1)})
        before = _fingerprint(ds)
        forged = copy.deepcopy(b1)
        forged[0]["ops"][0]["value"] = 999   # same (actor, seq), new body
        with pytest.raises(ProtocolError):
            handle.receive_msg({"docId": "doc", "clock": {"w": 1},
                                "changes": forged})
        assert _fingerprint(ds) == before


class TestGraduationParityUnderRedelivery:
    def test_wire_path_rejects_unknown_actions_before_graduation(self):
        """The wire grammar is closed at the sync layer: an unknown op
        action is a cheap typed rejection at validation time — the device
        tier never pays the O(history) oracle replay a hostile peer could
        otherwise trigger at will. (The direct backend API keeps the
        graduate-then-reject contract: tests/test_graduation.py.)"""
        device_backend.GRADUATION_STATS.clear()
        ds = DocSet()
        ds.set_doc("doc", _backend_doc("device", "h"))
        b1, _ = TestHubRedeliveryIdempotency()._batches("device")
        ds.deliver("doc", copy.deepcopy(b1))
        before = _fingerprint(ds)
        bad = [{"actor": "z", "seq": 1, "deps": {},
                "ops": [{"action": "frobnicate", "obj": am.ROOT_ID,
                         "key": "k"}]}]
        for _ in range(2):                  # redelivery of the bad batch
            with pytest.raises(ProtocolError):
                ds.deliver("doc", copy.deepcopy(bad))
            assert _fingerprint(ds) == before
        assert device_backend.GRADUATION_STATS == {}   # never replayed
        # the document lineage is still device-tier and still usable
        state = Frontend.get_backend_state(ds.get_doc("doc"))
        assert isinstance(state, device_backend.DeviceBackendState)
        ds.deliver("doc", copy.deepcopy(b1))      # dup of the good batch
        assert _fingerprint(ds) == before

    def test_direct_api_graduation_is_idempotent_under_redelivery(self):
        """Graduation-path parity: replaying the SAME out-of-scope
        delivery through the direct backend API graduates each time,
        rejects each time, and leaves the device lineage byte-identical
        and usable each time."""
        device_backend.GRADUATION_STATS.clear()
        doc = _backend_doc("device", "h")
        doc = am.change(doc, lambda d: d.__setitem__("x", 1))
        bad = [{"actor": "z", "seq": 1, "deps": {},
                "ops": [{"action": "frobnicate", "obj": am.ROOT_ID,
                         "key": "k"}]}]
        for n in (1, 2):
            with pytest.raises(ValueError, match="Unknown operation type"):
                am.apply_changes(doc, copy.deepcopy(bad))
            assert device_backend.GRADUATION_STATS == {"out_of_scope": n}
            assert am.to_json(doc) == {"x": 1}
        doc = am.change(doc, lambda d: d.__setitem__("y", 2))
        assert am.to_json(doc) == {"x": 1, "y": 2}

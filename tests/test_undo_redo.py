"""Undo/redo depth: list ops, merged-document interaction, stack discipline.

Mirrors the reference's undo/redo block (/root/reference/test/test.js:
956-1297): undo affects only the local actor's own changes, inverse ops are
synthesized against current state, and redo replays exactly what undo
removed.
"""

import pytest

import automerge_tpu as am


def set_(k, v):
    return lambda d: d.__setitem__(k, v)


class TestUndoListOps:
    def test_undo_list_insert(self):
        d = am.change(am.init(), set_("xs", ["a"]))
        d = am.change(d, lambda doc: doc["xs"].append("b"))
        d = am.undo(d)
        assert am.to_json(d) == {"xs": ["a"]}

    def test_undo_list_delete_restores_element(self):
        d = am.change(am.init(), set_("xs", ["a", "b", "c"]))
        d = am.change(d, lambda doc: doc["xs"].delete_at(1))
        assert am.to_json(d) == {"xs": ["a", "c"]}
        d = am.undo(d)
        assert am.to_json(d) == {"xs": ["a", "b", "c"]}

    def test_undo_list_set_restores_old_value(self):
        d = am.change(am.init(), set_("xs", ["a", "b"]))
        d = am.change(d, lambda doc: doc["xs"].__setitem__(0, "z"))
        d = am.undo(d)
        assert am.to_json(d) == {"xs": ["a", "b"]}

    def test_undo_redo_chain(self):
        d = am.change(am.init(), set_("xs", []))
        for c in "abc":
            d = am.change(d, lambda doc, c=c: doc["xs"].append(c))
        d = am.undo(am.undo(d))
        assert am.to_json(d) == {"xs": ["a"]}
        d = am.redo(d)
        assert am.to_json(d) == {"xs": ["a", "b"]}
        d = am.redo(d)
        assert am.to_json(d) == {"xs": ["a", "b", "c"]}
        assert not am.can_redo(d)

    def test_undo_text_edit(self):
        d = am.change(am.init(), set_("t", am.Text("hello")))
        d = am.change(d, lambda doc: doc["t"].delete_at(0, 2))
        assert str(d["t"]) == "llo"
        d = am.undo(d)
        assert str(d["t"]) == "hello"


class TestUndoWithMerges:
    def test_undo_skips_remote_changes(self):
        a = am.change(am.init("actor-1"), set_("mine", 1))
        b = am.change(am.init("actor-2"), set_("theirs", 2))
        merged = am.merge(a, b)
        undone = am.undo(merged)
        # only the local actor's change is undone
        assert am.to_json(undone) == {"theirs": 2}

    def test_undo_then_merge_converges(self):
        a = am.change(am.init("actor-1"), set_("x", 1))
        b = am.merge(am.init("actor-2"), a)
        a = am.undo(a)
        b = am.change(b, set_("y", 2))
        m1, m2 = am.merge(a, b), am.merge(b, a)
        assert am.to_json(m1) == am.to_json(m2) == {"y": 2}

    def test_undo_set_after_remote_overwrite_deletes_key(self):
        a = am.change(am.init("actor-1"), set_("k", "a-val"))
        b = am.merge(am.init("actor-2"), a)
        b = am.change(b, set_("k", "b-val"))
        merged = am.merge(a, b)           # b's later write overwrites
        # actor-1's inverse op is `del k`, issued with the merged clock as
        # deps — it causally covers b's write too, so the key disappears
        # (inverse ops are synthesized at change time, applied at undo time)
        undone = am.undo(merged)
        assert am.to_json(undone) == {}


class TestStackDiscipline:
    def test_interleaved_undo_redo_and_change(self):
        d = am.change(am.init(), set_("a", 1))
        d = am.change(d, set_("b", 2))
        d = am.undo(d)                     # removes b
        d = am.change(d, set_("c", 3))     # clears redo stack
        assert not am.can_redo(d)
        d = am.undo(d)                     # removes c
        d = am.undo(d)                     # removes a
        assert am.to_json(d) == {}
        assert not am.can_undo(d)

    def test_empty_change_undo_is_noop_then_pops_previous(self):
        d = am.change(am.init(), set_("a", 1))
        d2 = am.empty_change(d, "checkpoint")
        d3 = am.undo(d2)                   # pops the empty entry: no-op
        assert am.to_json(d3) == {"a": 1}
        d4 = am.undo(d3)                   # now pops the real change
        assert am.to_json(d4) == {}

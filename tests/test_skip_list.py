"""Order-statistic skip list tests: deterministic units with injected levels,
plus a randomized property test against a plain-list shadow model (the same
strategy as the reference suite, /root/reference/test/skip_list_test.js:171-224).
"""

import random

import pytest

from automerge_tpu.backend.skip_list import SkipList


def make(level_seq=None):
    return SkipList(level_source=iter(level_seq) if level_seq else None)


class TestBasics:
    def test_empty(self):
        s = SkipList()
        assert len(s) == 0
        assert s.index_of("a") == -1
        assert s.key_of(0) is None
        assert list(s) == []

    def test_insert_and_lookup(self):
        s = SkipList()
        s.insert_index(0, "a", 1)
        s.insert_index(1, "b", 2)
        s.insert_index(1, "c", 3)  # between a and b
        assert list(s) == ["a", "c", "b"]
        assert [s.index_of(k) for k in ("a", "c", "b")] == [0, 1, 2]
        assert [s.key_of(i) for i in range(3)] == ["a", "c", "b"]
        assert s.get_value("c") == 3

    def test_insert_after(self):
        s = SkipList()
        s.insert_after(None, "a", 1)
        s.insert_after("a", "b", 2)
        s.insert_after("a", "c", 3)
        assert list(s) == ["a", "c", "b"]

    def test_remove(self):
        s = SkipList()
        for i, k in enumerate("abcde"):
            s.insert_index(i, k, i)
        s.remove_index(2)
        assert list(s) == ["a", "b", "d", "e"]
        s.remove_key("a")
        assert list(s) == ["b", "d", "e"]
        assert s.index_of("a") == -1
        assert s.index_of("e") == 2

    def test_set_value(self):
        s = SkipList()
        s.insert_index(0, "a", 1)
        s.set_value("a", 42)
        assert s.get_value("a") == 42

    def test_duplicate_key_raises(self):
        s = SkipList()
        s.insert_index(0, "a")
        with pytest.raises(ValueError):
            s.insert_index(1, "a")

    def test_out_of_bounds(self):
        s = SkipList()
        with pytest.raises(IndexError):
            s.insert_index(1, "a")
        with pytest.raises(IndexError):
            s.remove_index(0)

    def test_injected_levels_deterministic(self):
        # Towers of explicit heights still index correctly.
        s = make(level_seq=[1, 3, 1, 2, 5, 1, 1, 2])
        for i, k in enumerate("abcdefgh"):
            s.insert_index(i, k)
        assert list(s) == list("abcdefgh")
        for i, k in enumerate("abcdefgh"):
            assert s.index_of(k) == i
            assert s.key_of(i) == k


def test_property_vs_shadow_model():
    rng = random.Random(20260729)
    s = SkipList(random_source=rng.random)
    shadow = []  # list of (key, value)
    next_key = 0
    for step in range(4000):
        op = rng.random()
        if op < 0.55 or not shadow:
            index = rng.randint(0, len(shadow))
            key = f"k{next_key}"
            next_key += 1
            s.insert_index(index, key, step)
            shadow.insert(index, (key, step))
        elif op < 0.8:
            index = rng.randrange(len(shadow))
            s.remove_index(index)
            del shadow[index]
        elif op < 0.9:
            index = rng.randrange(len(shadow))
            key, _ = shadow[index]
            s.set_value(key, step)
            shadow[index] = (key, step)
        else:
            index = rng.randrange(len(shadow))
            key, value = shadow[index]
            assert s.index_of(key) == index
            assert s.key_of(index) == key
            assert s.get_value(key) == value

    assert len(s) == len(shadow)
    assert list(s.items()) == shadow
    for i, (key, _) in enumerate(shadow):
        assert s.index_of(key) == i

"""Prefix-scan utilities: the device replacement for the skip list.

The reference maps elemId <-> visible index through an order-statistic skip
list (/root/reference/backend/skip_list.js). On device, the same queries are a
prefix sum over visibility flags in linearized order: `visible_index[i]` is
the rank of element i among visible elements — O(n) work, log depth, and it
batches over whole documents.

`visible_index` runs on the XLA path (cumsum fuses well). `scan_pallas.py`
holds the fused Pallas variant: one kernel computes the segment-rank,
segment-head, and visibility scans in a single HBM pass with SMEM carries
(designed for bandwidth parity with XLA's fused scans; the on-chip A/B
lives in profile_bench.py --pallas, see docs/MEASUREMENTS.md - and kept
as the building block for the sharded long-sequence case,
where the per-block carries become explicit ICI exchanges).
"""

from __future__ import annotations

import jax.numpy as jnp


def visible_index(pos: jnp.ndarray, visible: jnp.ndarray, capacity: int | None = None):
    """Rank among visible elements, by linearized position.

    pos: element positions from rga_linearize (head=-1, padding large).
    visible: bool per element (has at least one surviving value op).
    Returns (vis_rank, n_visible): vis_rank[i] = index of element i in the
    user-facing list (only meaningful where visible[i]), n_visible = total.
    """
    n = pos.shape[0]
    capacity = capacity or n
    # scatter visibility into position order, prefix-sum, gather back
    by_pos = jnp.zeros((capacity + 1,), dtype=jnp.int32)
    slot = jnp.clip(pos, 0, capacity)
    by_pos = by_pos.at[slot].add(visible.astype(jnp.int32))
    cum = jnp.cumsum(by_pos)
    # exclusive rank of the element at position p (clipped padding slots can
    # collide, but their ranks are never read)
    vis_rank = cum[slot] - by_pos[slot]
    n_visible = cum[capacity]
    return vis_rank, n_visible


def segment_starts(sorted_keys: jnp.ndarray) -> jnp.ndarray:
    """Boolean mask of group starts in a sorted key array."""
    return jnp.concatenate([jnp.array([True]), sorted_keys[1:] != sorted_keys[:-1]])

"""Top-level API: binds the frontend to an in-process backend.

Counterpart of /root/reference/src/automerge.js. Documents are immutable
values; every mutation returns a new document. ``save``/``load`` serialize the
change history as plain JSON (the reference uses transit-JSON; the logical
content — history ++ queue — is the same, src/automerge.js:59-66).
"""

from __future__ import annotations

import json

from . import frontend as Frontend
from .backend import default as Backend
from ._common import ROOT_ID
from ._uuid import uuid  # noqa: F401  (re-exported, like the reference)
from .frontend import Counter, Table, Text  # noqa: F401
from .resilience.validation import validate_save_payload

_SAVE_FORMAT = "automerge-tpu-v1"


def _doc_from_changes(options, changes):
    doc = init(options)
    state, _ = Backend.apply_changes(Backend.init(), changes)
    patch = Backend.get_patch(state)
    patch["state"] = state
    return Frontend.apply_patch(doc, patch)


def init(options=None):
    if isinstance(options, str):
        options = {"actorId": options}
    elif options is None:
        options = {}
    elif not isinstance(options, dict):
        raise TypeError(f"Unsupported options for init(): {options!r}")
    return Frontend.init({"backend": Backend.Backend, **options})


def from_(initial_state, options=None):
    new_doc = change(init(options), {"message": "Initialization", "undoable": False},
                     lambda doc: doc.update(initial_state))
    return new_doc


def change(doc, options=None, callback=None):
    new_doc, _ = Frontend.change(doc, options, callback)
    return new_doc


def empty_change(doc, options=None):
    new_doc, _ = Frontend.empty_change(doc, options)
    return new_doc


def undo(doc, options=None):
    new_doc, _ = Frontend.undo(doc, options)
    return new_doc


def redo(doc, options=None):
    new_doc, _ = Frontend.redo(doc, options)
    return new_doc


def save(doc, checkpoint=None) -> str:
    """Serialize a document's change history as plain JSON.

    With ``checkpoint=`` (a :class:`~.checkpoint.Checkpoint` or bundle
    bytes from :func:`~.checkpoint.checkpoint_doc`), the save is
    DELTA-COMPACTED: the
    change prefix the checkpoint's clock frontier covers is dropped and
    only the op-log tail is written; ``load`` then needs the same base
    checkpoint back (checkpoint/__init__.py, INTERNALS §8)."""
    state = Frontend.get_backend_state(doc)
    if checkpoint is not None:
        from .checkpoint import save_delta
        return save_delta(state, checkpoint)
    changes = state.history() + list(state.queue)
    return json.dumps({"format": _SAVE_FORMAT, "changes": changes})


def load(data: str, options=None, checkpoint=None):
    from .checkpoint import DELTA_FORMAT, load_delta
    payload = json.loads(data)
    # envelope validation (resilience.validation): non-dict payloads and a
    # missing/non-array `changes` raise a typed ProtocolError (a
    # ValueError) instead of leaking AttributeError/KeyError
    validate_save_payload(payload, require_changes=False)
    fmt = payload["format"]
    if fmt == DELTA_FORMAT:
        return load_delta(payload, checkpoint, options)
    if fmt != _SAVE_FORMAT:
        raise ValueError(f"Unsupported save format: {fmt!r}")
    validate_save_payload(payload, require_changes=True)
    return _doc_from_changes(options, payload["changes"])


def restore(checkpoint, options=None):
    """A document restored directly from a checkpoint bundle. Raises
    :class:`~.resilience.errors.CheckpointError` if the bundle is corrupt
    or truncated (every array is content-hashed)."""
    from .checkpoint import restore_doc
    return restore_doc(checkpoint, options)


def merge(local_doc, remote_doc):
    """Apply remote's changes to local (src/automerge.js:68-78)."""
    if Frontend.get_actor_id(local_doc) == Frontend.get_actor_id(remote_doc):
        raise ValueError("Cannot merge an actor with itself")
    local_state = Frontend.get_backend_state(local_doc)
    remote_state = Frontend.get_backend_state(remote_doc)
    state, patch = Backend.merge(local_state, remote_state)
    # "no diffs" does NOT mean "nothing applied": this backend emits NET
    # diffs, so a remote history whose net effect is zero (e.g. a delete
    # followed by its undo) applies real changes yet produces an empty
    # diff list. Returning local_doc then would silently drop those
    # changes from the returned lineage (they would never re-sync — the
    # clock says we have them). Short-circuit only when the clock proves
    # nothing was applied. The reference's diff-based guard
    # (src/automerge.js:68-78) is safe only under per-op diff emission.
    if not patch["diffs"] and patch["clock"] == dict(local_state.clock):
        return local_doc
    patch["state"] = state
    return Frontend.apply_patch(local_doc, patch)


def diff(old_doc, new_doc) -> list:
    old_state = Frontend.get_backend_state(old_doc)
    new_state = Frontend.get_backend_state(new_doc)
    changes = Backend.get_changes(old_state, new_state)
    _, patch = Backend.apply_changes(old_state, changes)
    return patch["diffs"]


def get_changes(old_doc, new_doc) -> list:
    old_state = Frontend.get_backend_state(old_doc)
    new_state = Frontend.get_backend_state(new_doc)
    return Backend.get_changes(old_state, new_state)


def get_all_changes(doc) -> list:
    return get_changes(init(), doc)


def apply_changes(doc, changes):
    old_state = Frontend.get_backend_state(doc)
    new_state, patch = Backend.apply_changes(old_state, changes)
    patch["state"] = new_state
    return Frontend.apply_patch(doc, patch)


def get_missing_deps(doc) -> dict:
    return Backend.get_missing_deps(Frontend.get_backend_state(doc))


def equals(val1, val2) -> bool:
    """Deep structural equality ignoring CRDT metadata (src/automerge.js:109-118)."""
    if isinstance(val1, dict) and isinstance(val2, dict):
        if set(val1.keys()) != set(val2.keys()):
            return False
        return all(equals(val1[k], val2[k]) for k in val1)
    if isinstance(val1, (list, tuple)) and isinstance(val2, (list, tuple)):
        return len(val1) == len(val2) and all(equals(a, b) for a, b in zip(val1, val2))
    return val1 == val2


class _HistoryEntry:
    """Lazy history item: the raw change plus a replayed snapshot
    (src/automerge.js:120-134)."""

    __slots__ = ("_history", "_index", "_actor")

    def __init__(self, history, index, actor):
        self._history = history
        self._index = index
        self._actor = actor

    @property
    def change(self):
        return self._history[self._index]

    @property
    def snapshot(self):
        return _doc_from_changes(self._actor, self._history[: self._index + 1])

    def __repr__(self):
        return f"<HistoryEntry seq={self._index + 1}>"


def get_history(doc) -> list:
    state = Frontend.get_backend_state(doc)
    actor = Frontend.get_actor_id(doc)
    history = state.history()
    return [_HistoryEntry(history, i, actor) for i in range(len(history))]


def to_json(doc):
    """Plain-Python snapshot of a document (dicts/lists/str values)."""
    def convert(value):
        if isinstance(value, Text):
            return str(value)
        if isinstance(value, Table):
            return {k: convert(v) for k, v in value.to_json().items()}
        if isinstance(value, Counter):
            return value.value
        if isinstance(value, dict):
            return {k: convert(v) for k, v in value.items()}
        if isinstance(value, list):
            return [convert(v) for v in value]
        return value
    return convert(doc)

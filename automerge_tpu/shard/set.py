"""The sharded serving tier: thousands of live docs partitioned across
the device mesh (INTERNALS §15).

``ShardedDocSet`` is the top of the tier: a population of engine docs
partitioned over N ``ShardLane``s by a deterministic
:class:`~.placement.PlacementTable`, with one single-device stacked
commit program per touched lane per serving round and NO multi-device
program anywhere on the commit path — the zero-collective invariant is
structural here (each lane's programs see one device), and
`shard/audit.py` proves the stronger SPMD claim from compiled HLO.

Causal admission lives at the ROUTER, not in the engine queues: a
delivery whose dependencies the target doc does not yet cover parks in a
bounded per-doc :class:`~..resilience.quarantine.QuarantineQueue`
(wire form) and is retried after every round that advances any clock.
Keeping the engine queues empty is what makes migration safe — a
checkpoint capture refuses a doc holding causally-unready queued
changes, and a router-held parked change trivially survives a move: the
drain resolves the owning lane at release time.

Hot-doc migration (the rebalance path, `shard/rebalance.py`) moves one
doc between lanes via a PR-3 checkpoint bundle at a commit boundary:

1. the doc is marked MIGRATING — deliveries arriving for it park in a
   dedicated migration pen (never half-applied on either lane);
2. the source lane captures + releases the doc (``lane.export``: the
   integrity-hashed columnar bundle);
3. the destination lane restores it (``lane.adopt``: tables staged onto
   the destination device);
4. the placement table records the move (the commit point), and the pen
   replays through the normal delivery gate — premature changes go back
   to quarantine, ready ones apply on the new owner.

Okapi's replication-group discipline (PAPERS.md) is why scale-out stays
cheap: causal metadata (clocks, dep closures, sync hubs) is per-doc /
per-room — shard-LOCAL — so adding lanes never grows a global clock.
"""

from __future__ import annotations

from .. import obs
from ..obs import lineage
from ..obs.telemetry import Telemetry
from ..resilience.inbound import _ready_under
from ..resilience.quarantine import QuarantineQueue
from .lane import ShardLane
from .placement import PlacementTable


def default_devices():
    import jax
    return list(jax.devices())


class ShardedDocSet:
    """A live-doc population served by N shard lanes over the mesh."""

    def __init__(self, n_shards: int = None, devices=None,
                 doc_kind: str = "text", capacity: int = 1024,
                 quarantine_capacity: int = 1024, telemetry=None,
                 assert_budget: bool = True, lanes=None):
        if lanes is not None:
            # adopt pre-built lanes (the service shares its tick-loop
            # lanes with the bulk doc mesh this way) — they already
            # carry a telemetry sink and device bindings
            self.telemetry = telemetry if telemetry is not None \
                else lanes[0].telemetry
            self.lanes = list(lanes)
            self.placement = PlacementTable(len(self.lanes))
        else:
            if devices is None:
                devices = default_devices()
            if n_shards is None:
                n_shards = len(devices)
            #: always-on rolling telemetry: per-lane admitted-ops windows
            #: (the rebalance policy's input) + migration counters
            self.telemetry = telemetry if telemetry is not None \
                else Telemetry()
            self.placement = PlacementTable(n_shards)
            self.lanes = [ShardLane(i, devices[i % len(devices)],
                                    telemetry=self.telemetry,
                                    assert_budget=assert_budget,
                                    doc_kind=doc_kind, capacity=capacity)
                          for i in range(n_shards)]
        self.doc_kind = doc_kind
        self.capacity = capacity
        self._quarantine: dict = {}     # doc_id -> QuarantineQueue
        self._quarantine_cap = quarantine_capacity
        self._migrating: dict = {}      # doc_id -> [parked deliveries]
        self.rebalancer = None          # attach_rebalancer installs one
        self.residency = None           # attach_residency installs one
        self._executor = None           # lazy LaneExecutor (parallel.py)
        self._predecoded: dict = {}     # doc_id -> (src changes, batch)
        self.stats = {"rounds": 0, "admitted_ops": 0, "parked": 0,
                      "released": 0, "migrations": 0,
                      "migrations_deferred": 0, "migration_parked": 0,
                      "peak_parked": 0}

    # -- parallel execution (INTERNALS §24) -----------------------------

    def executor(self):
        """The per-lane worker pool when parallel mesh execution is on
        (``AMTPU_PARALLEL_LANES`` — read per call so tests flip the
        flag mid-process), else None. Workers are persistent: created
        on first parallel round, reused until :meth:`close`."""
        from .parallel import LaneExecutor, parallel_lanes_enabled
        if not parallel_lanes_enabled(self.n_shards):
            return None
        if self._executor is None:
            self._executor = LaneExecutor(self.lanes,
                                          telemetry=self.telemetry)
        return self._executor

    def close(self):
        """Retire the worker pool (idempotent; a mesh without one is a
        no-op). Safe at any commit boundary — pending lane tasks drain
        before the workers exit."""
        if self._executor is not None:
            self._executor.close()
            self._executor = None

    # -- introspection --------------------------------------------------

    @property
    def n_shards(self) -> int:
        return len(self.lanes)

    def lane_of(self, doc_id: str) -> ShardLane:
        return self.lanes[self.placement.shard_of(doc_id)]

    def doc(self, doc_id: str):
        return self.lane_of(doc_id).docs.get(doc_id)

    def doc_ids(self) -> list:
        return sorted(d for lane in self.lanes for d in lane.docs)

    def quarantined(self, doc_id: str) -> int:
        q = self._quarantine.get(doc_id)
        return len(q) if q is not None else 0

    def describe(self) -> dict:
        """The tier's black-box snapshot: explicit placement entries,
        per-lane population/stats, quarantine occupancy."""
        return {
            "schema": "amtpu-shardmap-v1",
            "n_shards": self.n_shards,
            "devices": [str(lane.device) for lane in self.lanes],
            "placement_epoch": self.placement.epoch,
            "placement_overrides": self.placement.table(),
            "lanes": [{"index": lane.index, "device": str(lane.device),
                       "docs": sorted(lane.docs), "stats": dict(lane.stats)}
                      for lane in self.lanes],
            "quarantine": {d: len(q) for d, q in self._quarantine.items()
                           if len(q)},
            "migrating": sorted(self._migrating),
            "stats": dict(self.stats),
            **({"mesh_exec": self._executor.describe()}
               if self._executor is not None else {}),
            **({"residency": self.residency.describe()}
               if self.residency is not None else {}),
        }

    # -- the delivery gate ----------------------------------------------

    @staticmethod
    def _split_ready(changes, clock: dict):
        """Partition one delivery into (ready, premature) under `clock`,
        admitting in-delivery causal chains in any arrival order (the
        engine's scheduler handles the rounds; the router only refuses
        changes whose deps NOTHING in hand can satisfy). The serving
        hot path — one causally-ready change per doc per round — short-
        circuits before the fixpoint loop's clock copy."""
        if len(changes) == 1 and _ready_under(changes[0], clock):
            return list(changes), []
        ready, rest = [], list(changes)
        clock = dict(clock)
        progress = True
        while progress and rest:
            progress = False
            nxt = []
            for ch in rest:
                if _ready_under(ch, clock):
                    ready.append(ch)
                    if ch["seq"] > clock.get(ch["actor"], 0):
                        clock[ch["actor"]] = ch["seq"]
                    progress = True
                else:
                    nxt.append(ch)
            rest = nxt
        return ready, rest

    def _park(self, doc_id: str, changes, protect=()):
        q = self._quarantine.get(doc_id)
        if q is None:
            q = self._quarantine[doc_id] = QuarantineQueue(
                self._quarantine_cap)
        for ch in changes:
            q.park(ch)
            self.stats["parked"] += 1
            if lineage.ENABLED:
                lineage.hop(ch["actor"], ch["seq"], "quar/park",
                            site="router", doc=doc_id)
        total = sum(len(q) for q in self._quarantine.values())
        if total > self.stats["peak_parked"]:
            self.stats["peak_parked"] = total
        if self.residency is not None:
            # admission-aware prefetch: a park means this doc's missing
            # dependencies are in flight — a demoted doc starts staging
            # back before the release needs it (without evicting docs
            # the caller routed but has not yet ingested)
            self.residency.hint_park(doc_id, changes, protect=protect)

    def deliver(self, doc_id: str, changes) -> int:
        """Single-doc convenience wrapper over :meth:`deliver_round`."""
        return self.deliver_round({doc_id: changes})

    def deliver_rounds(self, rounds) -> int:
        """Serve a queued sequence of rounds with the lane-level round
        pipelining seam (INTERNALS §24): while the lane workers execute
        round t's device leg, the caller pre-decodes round t+1's wire
        payloads into columnar batches — the state-independent half of
        host planning (``_decode_wire`` reads only the payload and the
        doc's id), extending the PR-2/4 `PipelinedIngestor` chaining
        discipline from per-doc to per-lane. Admission (the state-
        dependent half) still runs in round order on the caller thread,
        and a batch only substitutes for its source list when the round
        admits it whole and in order — byte-identical to the sequential
        path by construction. With parallel execution off this is a
        plain :meth:`deliver_round` loop."""
        rounds = list(rounds)
        total = 0
        try:
            for i, chunk in enumerate(rounds):
                nxt = rounds[i + 1] if i + 1 < len(rounds) else None
                total += self.deliver_round(
                    chunk, _next_round=nxt if nxt else None)
        finally:
            # anything pre-decoded but never routed (an aborted run, a
            # doc that migrated away) must not outlive the sequence
            self._predecoded.clear()
        return total

    def _predecode_round(self, deliveries: dict) -> int:
        """Decode the next round's wire payloads (pure host: columnar
        batch build, cached per delivery list) — the work the executor
        overlaps with the in-flight round. Only docs that are already
        materialized and unambiguous (not migrating, not demoted to the
        store) pre-decode; everything else decodes in-round exactly as
        before."""
        n = 0
        for doc_id, changes in deliveries.items():
            if doc_id in self._predecoded or doc_id in self._migrating:
                continue
            if not isinstance(changes, list) or not changes \
                    or not all(isinstance(c, dict) for c in changes):
                continue
            if self.residency is not None \
                    and doc_id in self.residency.store:
                continue
            doc = self.lane_of(doc_id).docs.get(doc_id)
            if doc is None:
                continue
            try:
                batch = doc._decode_wire(changes)
            except Exception:
                continue    # poison payloads fail in-round, as before
            self._predecoded[doc_id] = (changes, batch)
            n += 1
        return n

    def deliver_round(self, deliveries: dict, _next_round: dict = None) \
            -> int:
        """One serving round: route ``{doc_id: [wire changes]}`` across
        the lanes (ready changes grouped into ONE stacked apply per
        touched lane), park premature changes in the per-doc quarantine,
        pen deliveries for migrating docs, then drain every quarantine
        the round unblocked. Returns the admitted wire-op count. The end
        of the round is a commit boundary: the attached rebalancer (if
        any) runs its policy here. `_next_round` is
        :meth:`deliver_rounds`' pipelining seam — the following round's
        deliveries, pre-decoded while this round's lane work drains."""
        _t0 = obs.now() if obs.ENABLED else 0
        if self.residency is not None:
            # the demand-paging gate: stored docs this round touches
            # page in and the eviction pass makes room BEFORE any lane
            # ingest can roll the footprint gauge past the budget
            self.residency.before_round(deliveries)
        per_lane: dict = {}
        for doc_id, changes in deliveries.items():
            pre = self._predecoded.pop(doc_id, None) \
                if self._predecoded else None
            orig = changes
            changes = list(changes)
            if doc_id in self._migrating:
                # the migration pen: the doc has no owner this instant —
                # nothing may apply until the new shard owns it
                self._migrating[doc_id].append(changes)
                self.stats["migration_parked"] += len(changes)
                if lineage.ENABLED:
                    lineage.hop_delivery(changes, "quar/pen",
                                         site="router", doc=doc_id)
                continue
            if self.residency is not None \
                    and doc_id in self.residency.store:
                # the doc's live state IS its stored bundle (before_round
                # judged nothing ready against the stored frontier):
                # routing here would ensure_doc a FRESH empty doc and
                # replay history over it — park everything instead; the
                # park hint prefetches, and the drain releases against
                # the live clock once the doc is resident again
                self._park(doc_id, changes, protect=tuple(deliveries))
                continue
            lane = self.lane_of(doc_id)
            doc = lane.docs.get(doc_id)
            ready, premature = self._split_ready(
                changes, doc.clock if doc is not None else {})
            if premature:
                self._park(doc_id, premature, protect=tuple(deliveries))
                # a park prefetch hint may have paged the doc in with
                # budget-aware placement — re-resolve the owner
                lane = self.lane_of(doc_id)
            if ready:
                if (pre is not None and not premature
                        and pre[0] is orig and len(ready) == len(changes)
                        and all(a is b for a, b in zip(ready, changes))):
                    # the whole delivery admitted, in arrival order: the
                    # pre-decoded batch IS what apply_stacked would have
                    # decoded in-round (same decoder, same payload) —
                    # hand the lane the batch, skipping the in-round
                    # decode the overlap already paid for
                    ready = pre[1]
                per_lane.setdefault(lane.index, {})[doc_id] = ready
        admitted = self._ingest_per_lane(per_lane, _next_round)
        admitted += self._drain_quarantine()
        self.stats["rounds"] += 1
        self.stats["admitted_ops"] += admitted
        if obs.ENABLED:
            obs.span("shard", "round", _t0, args={
                "docs": len(deliveries), "admitted_ops": admitted})
        if self.residency is not None:
            self.residency.after_round(deliveries)
        if self.rebalancer is not None:
            self.rebalancer.maybe_rebalance()
        return admitted

    def _ingest_per_lane(self, per_lane: dict, next_round: dict = None) \
            -> int:
        """Fan one routed round out across its touched lanes. With
        parallel execution on (shard/parallel.py) every touched lane's
        worker runs its stacked ingest concurrently and the caller
        pre-decodes `next_round` while the device legs drain; the
        sequential loop below is kept verbatim as the parity
        comparator. Either way the return is the round's admitted
        wire-op count and the caller resumes at a full barrier."""
        if not per_lane:
            return 0
        ex = self.executor()
        if ex is not None:
            return self._ingest_parallel(ex, per_lane, next_round)
        admitted = 0
        for idx in sorted(per_lane):
            admitted += self.lanes[idx].ingest(per_lane[idx])
            if lineage.ENABLED:
                self._hop_committed(idx, per_lane[idx])
        return admitted

    def _ingest_parallel(self, ex, per_lane: dict,
                         next_round: dict = None) -> int:
        """The concurrent leg: one task per touched lane on its
        persistent worker, per-worker stats deltas folded at the round
        barrier (no lost updates), lineage commit hops emitted
        caller-thread after the barrier (deterministic order). A worker
        error (budget assert included) re-raises on the caller AFTER
        every lane quiesced — completed lanes' stats still fold, like
        the sequential loop's partial progress."""
        tasks = []
        for idx in sorted(per_lane):
            lane = self.lanes[idx]
            delta = lane.stats_delta()
            tasks.append((idx, delta, ex.submit(
                idx, lane.ingest, per_lane[idx], stats=delta)))
        overlap = None
        if next_round:
            def overlap():
                n = self._predecode_round(next_round)
                if n:
                    ex.stats["rounds_overlapped"] += 1
                    ex.stats["predecoded_batches"] += n
        try:
            ex.barrier([t for _, _, t in tasks], while_waiting=overlap)
        finally:
            for idx, delta, task in tasks:
                if task.error is None and task.done():
                    lane_stats = self.lanes[idx].stats
                    for k, v in delta.items():
                        if v:
                            lane_stats[k] += v
        admitted = 0
        for idx, delta, task in tasks:
            admitted += task.result
            if lineage.ENABLED:
                self._hop_committed(idx, per_lane[idx])
        return admitted

    def _drain_quarantine(self) -> int:
        """Retry every parked change against the live clocks until a
        fixpoint; released changes ride a normal lane ingest (grouped
        per lane per iteration)."""
        admitted = 0
        progress = True
        while progress:
            progress = False
            per_lane: dict = {}
            routed: list = []   # released docs awaiting ingest — a
            #                     later page-in must not evict them
            for doc_id, q in list(self._quarantine.items()):
                if not len(q) or doc_id in self._migrating:
                    continue
                stored = (self.residency is not None
                          and doc_id in self.residency.store)
                if stored:
                    # judge readiness against the STORED frontier (the
                    # bundle manifest's clock) — only a releasable
                    # change justifies paging the doc in; an all-
                    # premature quarantine leaves it demoted
                    clock = self.residency.stored_clock(doc_id) or {}
                else:
                    doc = self.lane_of(doc_id).docs.get(doc_id)
                    clock = doc.clock if doc is not None else {}
                parked = q.drain()
                ready, premature = self._split_ready(parked, clock)
                for ch in premature:
                    q.park(ch, requeue=True)
                if ready:
                    if stored:
                        # admission hint: the release is about to
                        # ingest — page in now (and resolve the lane
                        # AFTER, page-in placement is budget-aware)
                        self.residency.hint_release(
                            doc_id, protect=tuple(routed) + (doc_id,))
                    lane = self.lane_of(doc_id)
                    per_lane.setdefault(lane.index, {})[doc_id] = ready
                    routed.append(doc_id)
                    self.stats["released"] += len(ready)
                    if lineage.ENABLED:
                        lineage.hop_delivery(ready, "quar/release",
                                             site="router", doc=doc_id)
            if per_lane:
                # releases ride the same fan-out as the round proper
                # (parallel when enabled, the verbatim sequential loop
                # otherwise); each fixpoint iteration barriers before
                # re-judging clocks, so causal ordering is untouched
                admitted += self._ingest_per_lane(per_lane)
                progress = True
        return admitted

    def _hop_committed(self, lane_idx: int, deliveries: dict):
        """Visibility hops for a lane ingest: every sampled change the
        router just handed the lane is now committed on that lane's
        replica (one hop per change per lane site)."""
        site = f"lane{lane_idx}"
        for doc_id, changes in deliveries.items():
            lineage.hop_delivery(changes, "commit", site=site, doc=doc_id)

    # -- migration ------------------------------------------------------

    def attach_rebalancer(self, **kwargs):
        from .rebalance import Rebalancer
        self.rebalancer = Rebalancer(self, **kwargs)
        return self.rebalancer

    def attach_residency(self, **kwargs):
        """Install the device-residency tier (INTERNALS §22): demand
        paging, budget-driven eviction to host bundles, disk aging."""
        from ..residency import ResidencyManager
        self.residency = ResidencyManager(self, **kwargs)
        return self.residency

    def migrate(self, doc_id: str, dst_shard: int,
                _mid_migration=None) -> bool:
        """Move one doc to `dst_shard` via a checkpoint bundle at a
        commit boundary. Returns False (nothing moved) when the doc is
        already home, or when its engine still holds causally-unready
        queued work — migration DEFERS rather than strand a causal hole
        (the next boundary retries). ``_mid_migration`` is the test seam
        for the quarantine handshake: called while the doc has no owner,
        so injected deliveries must pen and replay."""
        src_shard = self.placement.shard_of(doc_id)
        if not 0 <= dst_shard < self.n_shards:
            raise ValueError(f"no shard {dst_shard}")
        if dst_shard == src_shard:
            return False
        src = self.lanes[src_shard]
        doc = src.docs.get(doc_id)
        if doc is None:
            # never materialized here: ownership is just a table entry
            self.placement.move(doc_id, dst_shard)
            return True
        if doc.queue:
            self.stats["migrations_deferred"] += 1
            return False
        _t0 = obs.now() if obs.ENABLED else 0
        self._migrating[doc_id] = []
        moved = False
        try:
            bundle = src.export(doc_id)
            try:
                if _mid_migration is not None:
                    _mid_migration()
                self.lanes[dst_shard].adopt(doc_id, bundle)
                self.placement.move(doc_id, dst_shard)
                moved = True
            except BaseException:
                # failure atomicity: a failed adopt must not lose the
                # doc — restore residency on the SOURCE lane from the
                # bundle already in hand (placement never moved, so
                # ownership and state stay consistent) and let the
                # penned deliveries replay against it below
                src.adopt(doc_id, bundle)
                src.stats["docs_in"] -= 1       # a rollback, not a move
                src.stats["docs_out"] -= 1
                raise
        finally:
            # whatever happened, the doc has an owner again: replay the
            # pen through the normal gate — ready changes apply there,
            # premature ones go (back) to quarantine
            penned = self._migrating.pop(doc_id, [])
            for changes in penned:
                self.deliver_round({doc_id: changes})
        self.stats["migrations"] += 1
        self.telemetry.observe_count("shard", "migrations")
        if obs.ENABLED:
            obs.span("shard", "migrate", _t0, args={
                "doc": doc_id, "src": src_shard, "dst": dst_shard,
                "bundle_bytes": len(bundle), "penned": len(penned)})
        return moved

    # -- reads ----------------------------------------------------------

    def texts(self) -> dict:
        out = {}
        for lane in self.lanes:
            out.update(lane.texts())
        return out

    def capture(self, doc_id: str) -> bytes:
        """The doc's integrity-hashed checkpoint bundle (byte-
        deterministic for a given state — the shard-count-invariance
        soak compares exactly these bytes across mesh sizes)."""
        from ..checkpoint import capture_engine
        if self.residency is not None:
            # a demoted doc's checkpoint IS its stored bundle — it was
            # produced by this same capture at demotion, byte-identical
            bundle = self.residency.stored_bundle(doc_id)
            if bundle is not None:
                return bundle
        lane = self.lane_of(doc_id)
        with lane.device_ctx():
            return capture_engine(lane.docs[doc_id])

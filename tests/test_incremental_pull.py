"""Incremental text pull (engine/text_doc host cache + dirty spans):
byte-for-byte equivalence with the full pull across random merge/delete/
overwrite rounds, and the O(edits)-bytes-moved contract asserted on the
engine-reported span bytes (not wall clock)."""

import numpy as np

import bench as B
from automerge_tpu.engine import DeviceTextDoc, TextChangeBatch

from test_prepare_commit import typing_change


def make_doc(n=6000, incremental=True):
    d = DeviceTextDoc("t")
    d.eager_materialize = True
    d.incremental_pull = incremental
    d.incremental_pull_min = 64        # engage on test-sized docs
    d.apply_batch(B.base_batch("t", n))
    d.text()
    return d


def test_incremental_equals_full_random_rounds():
    """Random concurrent merges, deletes, and overwrites, pulling after
    every round: the incremental path must match a full-pull control doc
    exactly, and actually run incrementally on the merge rounds."""
    rng = np.random.default_rng(42)
    n = 6000
    doc = make_doc(n)
    control = make_doc(n, incremental=False)
    saw_incremental = 0
    for r in range(6):
        kind = r % 3
        if kind == 0:          # concurrent typing runs at random spots
            batch = B.merge_batch("t", 6, 20, n, seed=100 + r,
                                  actor_prefix=f"m{r:02d}")
            rebuilt = B.merge_batch("t", 6, 20, n, seed=100 + r,
                                    actor_prefix=f"m{r:02d}")
        elif kind == 1:        # deletes of random base elements
            targets = rng.choice(np.arange(1, n), size=15, replace=False)
            changes = [{"actor": f"d{r:02d}", "seq": 1,
                        "deps": {"base": 1},
                        "ops": [{"action": "del", "obj": "t",
                                 "key": f"base:{int(t)}"}
                                for t in targets]}]
            batch = TextChangeBatch.from_changes(changes, "t")
            rebuilt = TextChangeBatch.from_changes(changes, "t")
        else:                  # overwrites of random base elements
            targets = rng.choice(np.arange(1, n), size=12, replace=False)
            changes = [{"actor": f"o{r:02d}", "seq": 1,
                        "deps": {"base": 1},
                        "ops": [{"action": "set", "obj": "t",
                                 "key": f"base:{int(t)}",
                                 "value": chr(65 + (int(t) % 26))}
                                for t in targets]}]
            batch = TextChangeBatch.from_changes(changes, "t")
            rebuilt = TextChangeBatch.from_changes(changes, "t")
        doc.apply_batch(batch)
        control.apply_batch(rebuilt)
        assert doc.text() == control.text(), f"round {r} diverged"
        if doc.pull_stats["mode"] == "incremental":
            saw_incremental += 1
            if kind == 0:
                # merge rounds are O(edits); assign/delete rounds dirty
                # at SEGMENT granularity by design (the touched slot's
                # whole containing segment re-pulls — see INTERNALS)
                assert doc.pull_stats["span_bytes"] < n // 2, \
                    doc.pull_stats
    assert saw_incremental >= 4, (
        f"incremental path engaged only {saw_incremental}/6 rounds")


def test_incremental_moves_o_edits_bytes():
    """A small merge into a large warm doc ships span bytes proportional
    to the EDIT, not the document (the ISSUE 2 acceptance assertion)."""
    n = 50_000
    doc = make_doc(n)
    assert doc._text_cache is not None
    edit_chars = 10 * 15            # 10 actors x 15 visible chars
    doc.apply_batch(B.merge_batch("t", 10, 30, n, seed=7,
                                  actor_prefix="sm"))
    text = doc.text()
    assert len(text) == n + edit_chars
    stats = doc.pull_stats
    assert stats["mode"] == "incremental", stats
    assert stats["span_bytes"] <= 4 * edit_chars, stats
    assert stats["span_bytes"] < (n + edit_chars) // 50, stats


def test_repeat_pull_is_cached():
    doc = make_doc(2000)
    doc.apply_batch(B.merge_batch("t", 3, 10, 2000, seed=1,
                                  actor_prefix="q"))
    t1 = doc.text()
    t2 = doc.text()
    assert t1 == t2
    assert doc.pull_stats["mode"] == "cached"
    assert doc.pull_stats["span_bytes"] == 0


def test_non_ascii_falls_back_to_full():
    """A non-7-bit value disables the u8 codes path; pulls degrade to
    full and stay correct."""
    doc = make_doc(2000)
    control = make_doc(2000, incremental=False)
    ch = [{"actor": "uni", "seq": 1, "deps": {"base": 1},
           "ops": [{"action": "set", "obj": "t", "key": "base:10",
                    "value": "é"}]}]
    doc.apply_batch(TextChangeBatch.from_changes(ch, "t"))
    control.apply_batch(TextChangeBatch.from_changes(ch, "t"))
    assert doc.text() == control.text()
    assert doc.pull_stats["mode"] == "full"
    # and later pulls keep working
    doc.apply_batch(B.merge_batch("t", 2, 10, 2000, seed=3,
                                  actor_prefix="r"))
    control.apply_batch(B.merge_batch("t", 2, 10, 2000, seed=3,
                                      actor_prefix="r"))
    assert doc.text() == control.text()


def test_incremental_across_multi_round_batches():
    """Causally chained two-round batches (seq 2 on seq 1) reconcile
    incrementally too — the dirty feed accumulates across rounds."""
    doc = make_doc(3000)
    control = make_doc(3000, incremental=False)
    changes = [
        typing_change("alice", 1, {"base": 1}, "AAAA", 100, "base:50"),
        typing_change("alice", 2, {}, "BB", 200, "alice:103"),
    ]
    doc.apply_batch(TextChangeBatch.from_changes(changes, "t"))
    control.apply_batch(TextChangeBatch.from_changes(changes, "t"))
    assert doc.text() == control.text()
    assert doc.pull_stats["mode"] == "incremental"
    assert doc.pull_stats["span_bytes"] <= 24


def test_ascii_flip_drops_cache_and_touch_feed():
    """A non-ascii round permanently disables the incremental path; the
    cache and the touched-slot accumulator must drop with it, not leak
    for the document's remaining life."""
    doc = make_doc(6000)
    assert doc._text_cache is not None
    ch = [{"actor": "uni", "seq": 1, "deps": {"base": 1},
           "ops": [{"action": "set", "obj": "t", "key": "base:10",
                    "value": "ü"}]}]
    doc.apply_batch(TextChangeBatch.from_changes(ch, "t"))
    assert doc._text_cache is None
    assert doc._touched_old == []
    # later assign rounds must not accumulate either
    ch2 = [{"actor": "uni", "seq": 2, "deps": {},
            "ops": [{"action": "set", "obj": "t", "key": "base:11",
                     "value": "x"}]}]
    doc.apply_batch(TextChangeBatch.from_changes(ch2, "t"))
    assert doc._touched_old == []


def test_disabled_flag_stays_full():
    doc = make_doc(2000, incremental=False)
    doc.apply_batch(B.merge_batch("t", 2, 10, 2000, seed=2,
                                  actor_prefix="s"))
    doc.text()
    assert doc.pull_stats["mode"] == "full"

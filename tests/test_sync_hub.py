"""SyncHub: N peers served from one DocSet with ONE batched clock
comparison per local change (the vectorized getMissingChanges of SURVEY §5).
Wire compatibility: hub peers interoperate with plain Connections."""

from unittest import mock

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from automerge_tpu.sync import ClockMatrix, Connection, DocSet, SyncHub


class Pipe:
    """In-process bidirectional message pipe with explicit pumping."""

    def __init__(self):
        self.a_to_b: list = []
        self.b_to_a: list = []

    def pump(self, b_receive, a_receive) -> int:
        n = 0
        while self.a_to_b or self.b_to_a:
            while self.a_to_b:
                b_receive(self.a_to_b.pop(0))
                n += 1
            while self.b_to_a:
                a_receive(self.b_to_a.pop(0))
                n += 1
        return n


def test_clock_matrix_pending_is_batched():
    m = ClockMatrix()
    for d in range(3):
        m.update_ours(f"doc{d}", {"alice": 2, "bob": 1})
    for p in range(4):
        for d in range(3):
            m.set_active(f"peer{p}", f"doc{d}")
            m.update_theirs(f"peer{p}", f"doc{d}", {"alice": 2, "bob": 1})
    assert m.pending() == []
    m.update_ours("doc1", {"alice": 3})
    assert sorted(m.pending()) == [(f"peer{p}", "doc1") for p in range(4)]
    m.update_theirs("peer2", "doc1", {"alice": 3})
    assert ("peer2", "doc1") not in m.pending()


def test_hub_broadcasts_one_change_to_all_peers():
    ds = DocSet()
    hub = SyncHub(ds)
    outboxes = {p: [] for p in ("p1", "p2", "p3")}
    handles = {p: hub.add_peer(p, outboxes[p].append) for p in outboxes}
    hub.open()

    doc = am.change(am.init("alice"), lambda d: d.__setitem__("x", 1))
    ds.set_doc("doc1", doc)
    # unknown peers first get an advertisement, never speculative changes
    for p, box in outboxes.items():
        assert [m for m in box if m.get("changes")] == []
        assert any(m["docId"] == "doc1" for m in box), (p, box)
    # each peer reveals its (empty) clock; the hub then sends the changes
    for p, h in handles.items():
        h.receive_msg({"docId": "doc1", "clock": {}})
    for p, box in outboxes.items():
        with_changes = [m for m in box if m.get("changes")]
        assert len(with_changes) == 1, (p, box)
        assert with_changes[0]["docId"] == "doc1"

    # a subsequent local change now broadcasts changes directly
    ds.set_doc("doc1", am.change(ds.get_doc("doc1"),
                                 lambda d: d.__setitem__("y", 2)))
    for p, box in outboxes.items():
        assert len([m for m in box if m.get("changes")]) == 2, (p, box)


def test_hub_uses_one_batched_comparison_per_change():
    ds = DocSet()
    hub = SyncHub(ds)
    for p in range(5):
        hub.add_peer(f"p{p}", lambda m: None)
    hub.open()
    with mock.patch.object(ClockMatrix, "pending",
                           wraps=hub._matrix.pending) as spy:
        doc = am.change(am.init("alice"), lambda d: d.__setitem__("x", 1))
        ds.set_doc("doc1", doc)
        # one local change -> ONE batched pending() call serves all 5 peers
        assert spy.call_count == 1


def test_n_connections_share_one_hub_and_one_diff():
    """Connections are hub-backed: N Connections on one DocSet share ONE
    ClockMatrix (one batched pending() per local change) and, once the
    peers' believed clocks agree, ONE get_missing_changes extraction
    serves all N (the reference's per-Connection loop would diff N times,
    src/connection.js:58-74)."""
    from automerge_tpu.sync import hub as hub_mod

    ds = DocSet()
    boxes = [[] for _ in range(3)]
    conns = [Connection(ds, boxes[i].append) for i in range(3)]
    for c in conns:
        c.open()
    # all three faces share the doc-set's one hub
    assert len({id(c._hub) for c in conns}) == 1
    hub = conns[0]._hub

    ds.set_doc("doc", am.change(am.init("alice"),
                                lambda d: d.__setitem__("x", 1)))
    for c in conns:   # every peer reveals its (empty) clock
        c.receive_msg({"docId": "doc", "clock": {}})
    for box in boxes:
        assert sum(1 for m in box if m.get("changes")) == 1

    with mock.patch.object(ClockMatrix, "pending",
                           wraps=hub._matrix.pending) as pend, \
         mock.patch.object(hub_mod.Backend, "get_missing_changes",
                           wraps=hub_mod.Backend.get_missing_changes) as gmc:
        ds.set_doc("doc", am.change(ds.get_doc("doc"),
                                    lambda d: d.__setitem__("y", 2)))
        # one local change: ONE batched comparison, ONE shared extraction
        assert pend.call_count == 1
        assert gmc.call_count == 1
    for box in boxes:
        assert sum(1 for m in box if m.get("changes")) == 2


def test_hub_interoperates_with_plain_connection():
    # hub side: two docs
    ds_hub = DocSet()
    hub = SyncHub(ds_hub)
    # peer side: a reference-parity Connection
    ds_peer = DocSet()
    pipe = Pipe()
    peer_handle = hub.add_peer("peer", pipe.a_to_b.append)
    conn = Connection(ds_peer, pipe.b_to_a.append)
    hub.open()
    conn.open()

    d1 = am.change(am.init("alice"), lambda d: d.__setitem__("x", 1))
    ds_hub.set_doc("doc1", d1)
    pipe.pump(conn.receive_msg, peer_handle.receive_msg)
    assert am.to_json(ds_peer.get_doc("doc1")) == {"x": 1}

    # and back: peer edits, hub side converges
    d2 = am.change(ds_peer.get_doc("doc1"),
                   lambda d: d.__setitem__("y", 2))
    ds_peer.set_doc("doc1", d2)
    pipe.pump(conn.receive_msg, peer_handle.receive_msg)
    assert am.to_json(ds_hub.get_doc("doc1")) == {"x": 1, "y": 2}


def test_hub_to_hub_multi_doc_convergence():
    ds_a, ds_b = DocSet(), DocSet()
    hub_a, hub_b = SyncHub(ds_a), SyncHub(ds_b)
    pipe = Pipe()
    pa = hub_a.add_peer("b", pipe.a_to_b.append)
    pb = hub_b.add_peer("a", pipe.b_to_a.append)
    hub_a.open()
    hub_b.open()

    for i in range(3):
        doc = am.change(am.init(f"actor{i}"),
                        lambda d, i=i: d.__setitem__("n", i))
        ds_a.set_doc(f"doc{i}", doc)
    pipe.pump(pb.receive_msg, pa.receive_msg)
    for i in range(3):
        assert am.to_json(ds_b.get_doc(f"doc{i}")) == {"n": i}

    # concurrent edits on both sides, one pump converges everything
    ds_a.set_doc("doc0", am.change(ds_a.get_doc("doc0"),
                                   lambda d: d.__setitem__("a", 1)))
    ds_b.set_doc("doc1", am.change(ds_b.get_doc("doc1"),
                                   lambda d: d.__setitem__("b", 2)))
    pipe.pump(pb.receive_msg, pa.receive_msg)
    assert am.to_json(ds_a.get_doc("doc1")) == am.to_json(ds_b.get_doc("doc1"))
    assert am.to_json(ds_a.get_doc("doc0")) == am.to_json(ds_b.get_doc("doc0"))


def test_no_speculative_changes_for_unrevealed_doc():
    """A peer that revealed a clock for doc A must still only get an
    advertisement for a new doc B (Connection's unknown-peer behavior)."""
    ds = DocSet()
    hub = SyncHub(ds)
    box = []
    h = hub.add_peer("p", box.append)
    hub.open()
    ds.set_doc("A", am.change(am.init("alice"), lambda d: d.__setitem__("a", 1)))
    h.receive_msg({"docId": "A", "clock": {}})
    assert [m["docId"] for m in box if m.get("changes")] == ["A"]
    box.clear()
    ds.set_doc("B", am.change(am.init("bob"), lambda d: d.__setitem__("b", 2)))
    assert [m for m in box if m.get("changes")] == [], box
    assert any(m["docId"] == "B" and "changes" not in m for m in box)


def test_readded_peer_syncs_fresh():
    ds = DocSet()
    hub = SyncHub(ds)
    box = []
    h = hub.add_peer("q", box.append)
    hub.open()
    ds.set_doc("D", am.change(am.init("alice"), lambda d: d.__setitem__("x", 1)))
    h.receive_msg({"docId": "D", "clock": {}})
    assert any(m.get("changes") for m in box)
    hub.remove_peer("q")
    box2 = []
    h2 = hub.add_peer("q", box2.append)
    h2.receive_msg({"docId": "D", "clock": {}})
    assert any(m.get("changes") for m in box2), box2


def test_readded_peer_rerequests_doc_from_prior_session():
    """Churn regression (add -> remove -> re-add mid-sync): the
    don't-re-request-removed-docs guard is scoped to one peer SESSION.
    A doc the hub held during an old peer session (then removed locally)
    must be re-requested when the RE-ADDED peer offers it — the old
    hub-global `_had_doc` suppressed this forever."""
    ds = DocSet()
    hub = SyncHub(ds)
    box = []
    h = hub.add_peer("q", box.append)
    hub.open()
    # session 1: the peer syncs doc D to us mid-sync
    src = am.change(am.init("w"), lambda d: d.__setitem__("x", 1))
    h.receive_msg({"docId": "D", "clock": {"w": 1},
                   "changes": am.get_all_changes(src)})
    assert am.to_json(ds.get_doc("D")) == {"x": 1}
    # we drop the doc locally, and the peer churns
    ds.remove_doc("D")
    hub.remove_peer("q")
    box2 = []
    h2 = hub.add_peer("q", box2.append)
    # session 2: the same-id peer re-offers D -> must be re-requested
    h2.receive_msg({"docId": "D", "clock": {"w": 1}})
    requests = [m for m in box2 if m["docId"] == "D" and m["clock"] == {}]
    assert requests, f"re-add suppressed the re-request: {box2}"
    # and the peer's answer resurrects the doc for the new session
    h2.receive_msg({"docId": "D", "clock": {"w": 1},
                    "changes": am.get_all_changes(src)})
    assert am.to_json(ds.get_doc("D")) == {"x": 1}


def test_same_session_removed_doc_still_not_rerequested():
    """The counterpart: WITHIN one peer session the guard still holds
    (mirrors test_removed_doc_neither_crashes_nor_resurrects, pinned here
    against the session-scoped rewrite)."""
    ds = DocSet()
    hub = SyncHub(ds)
    box = []
    h = hub.add_peer("p", box.append)
    hub.open()
    src = am.change(am.init("w"), lambda d: d.__setitem__("x", 1))
    h.receive_msg({"docId": "D", "clock": {"w": 1},
                   "changes": am.get_all_changes(src)})
    ds.remove_doc("D")
    box.clear()
    h.receive_msg({"docId": "D", "clock": {"w": 1}})
    assert [m for m in box if m["docId"] == "D"] == [], box


def test_late_message_for_removed_peer_absorbed_without_send():
    """A message in flight when remove_peer ran must neither KeyError nor
    write to the dead transport; change-bearing frames are still absorbed
    (the hub-side mirror of the closed-Connection contract)."""
    ds = DocSet()
    hub = SyncHub(ds)
    box = []
    h = hub.add_peer("p", box.append)
    hub.open()
    hub.remove_peer("p")
    box.clear()
    src = am.change(am.init("w"), lambda d: d.__setitem__("x", 1))
    h.receive_msg({"docId": "D", "clock": {"w": 1},
                   "changes": am.get_all_changes(src)})   # absorbed
    h.receive_msg({"docId": "D", "clock": {"w": 1}})      # no re-request
    assert box == []
    assert am.to_json(ds.get_doc("D")) == {"x": 1}


def test_removed_doc_neither_crashes_nor_resurrects():
    ds = DocSet()
    hub = SyncHub(ds)
    box = []
    h = hub.add_peer("p", box.append)
    hub.open()
    ds.set_doc("D", am.change(am.init("alice"), lambda d: d.__setitem__("x", 1)))
    h.receive_msg({"docId": "D", "clock": {}})
    ds.remove_doc("D")
    box.clear()
    # unrelated doc change must not crash on the removed doc
    ds.set_doc("E", am.change(am.init("bob"), lambda d: d.__setitem__("y", 2)))
    assert any(m["docId"] == "E" for m in box)
    # a peer advertising the removed doc must not trigger a re-request
    box.clear()
    h.receive_msg({"docId": "D", "clock": {"alice": 1}})
    assert [m for m in box if m["docId"] == "D"] == [], box


def test_unrevealed_and_removed_pairs_never_enter_pending():
    """pending() must not re-flag pairs flush() can never serve."""
    ds = DocSet()
    hub = SyncHub(ds)
    h = hub.add_peer("p", lambda m: None)
    hub.open()
    ds.set_doc("A", am.change(am.init("alice"), lambda d: d.__setitem__("a", 1)))
    ds.set_doc("B", am.change(am.init("bob"), lambda d: d.__setitem__("b", 2)))
    h.receive_msg({"docId": "A", "clock": {}})
    # B was never revealed by the peer: only caught-up A pairs exist
    assert hub._matrix.pending() == []
    hub.remove_peer("p")
    ds.set_doc("A", am.change(ds.get_doc("A"), lambda d: d.__setitem__("a2", 3)))
    assert hub._matrix.pending() == []


def test_covered_clock_pair_leaves_pending():
    """A pair whose raw clock is behind but transitively covered is
    recorded as caught-up after one flush (no perpetual re-diffing)."""
    ds = DocSet()
    hub = SyncHub(ds)
    h = hub.add_peer("p", lambda m: None)
    hub.open()
    a = am.change(am.init("alice"), lambda d: d.__setitem__("x", 1))
    b = am.merge(am.init("bob"), a)
    b = am.change(b, lambda d: d.__setitem__("y", 2))
    ds.set_doc("D", b)
    # peer reveals only bob's seq: transitively covers alice's change
    h.receive_msg({"docId": "D", "clock": {"bob": 1}})
    assert ("p", "D") not in hub._matrix.pending()


def test_missing_changes_fast_cover_path():
    """A peer whose clock covers the doc gets [] without a closure walk."""
    from automerge_tpu.backend import device as db
    d = am.change(am.init("alice"), lambda doc: doc.__setitem__("x", 1))
    d = am.change(d, lambda doc: doc.__setitem__("y", 2))
    state = Frontend.get_backend_state(d)
    assert db.get_missing_changes(state, dict(state.clock)) == []
    missing = db.get_missing_changes(state, {"alice": 1})
    assert len(missing) == 1 and missing[0]["seq"] == 2
    assert len(db.get_missing_changes(state, {})) == 2


def test_connection_close_unhooks_hub_from_docset():
    """When the last Connection closes, the hub unhooks from the DocSet:
    no handler remains, snapshot set_doc is legal again, and a reopened
    connection starts with fresh peer state."""
    ds = DocSet()
    d1 = am.change(am.init("alice"), lambda d: d.__setitem__("x", 1))
    ds.set_doc("doc", d1)
    c = Connection(ds, lambda m: None)
    c.open()
    assert len(ds._handlers) == 1
    d2 = am.change(d1, lambda d: d.__setitem__("y", 2))
    ds.set_doc("doc", d2)
    c.close()
    assert ds._handlers == []          # hub handler gone
    assert ds._sync_hub is None
    # with no connections, putting an older snapshot back is allowed
    # again (e.g. time-travel UI) — the hub's stale-state guard is gone
    ds.set_doc("doc", d1)
    ds.set_doc("doc", d2)
    c.open()                            # rejoining works, fresh state
    assert len(ds._handlers) == 1
    c.close()


def test_closed_connection_absorbs_late_messages_without_sending():
    """A late in-flight message delivered after close() must neither
    rejoin the hub nor write to the torn-down transport; inbound changes
    are still absorbed."""
    ds_a, ds_b = DocSet(), DocSet()
    out_a, out_b = [], []
    ca, cb = Connection(ds_a, out_a.append), Connection(ds_b, out_b.append)
    ds_a.set_doc("doc", am.change(am.init("alice"),
                                  lambda d: d.__setitem__("x", 1)))
    ca.open(); cb.open()
    while out_a or out_b:               # pump to quiescence
        while out_a:
            cb.receive_msg(out_a.pop(0))
        while out_b:
            ca.receive_msg(out_b.pop(0))
    assert am.to_json(ds_b.get_doc("doc")) == {"x": 1}

    # a sends one more change-bearing message, then b closes BEFORE it
    # arrives
    ds_a.set_doc("doc", am.change(ds_a.get_doc("doc"),
                                  lambda d: d.__setitem__("y", 2)))
    late = [m for m in out_a if m.get("changes")]
    assert late
    cb.close()
    n_sent = len(out_b)
    cb.receive_msg(late[0])             # late delivery after close
    assert len(out_b) == n_sent         # nothing written to dead transport
    assert ds_b._sync_hub is None       # did not rejoin
    assert am.to_json(ds_b.get_doc("doc")) == {"x": 1, "y": 2}  # absorbed


def test_lossy_network_recovers_on_reconnect():
    """Messages dropped at random are recovered by peer reconnection: a
    (re)joining peer is re-advertised everything, so a lossless exchange
    after reconnect converges every node — the protocol's recovery story
    (the reference's, too: re-sends happen on state change or peer (re)
    connect, never spontaneously)."""
    import random

    for seed in (1, 2, 3):
        rng = random.Random(41_000 + seed)
        sets = [DocSet() for _ in range(3)]
        queues = {(i, j): [] for i in range(3) for j in range(3) if i != j}
        conns = {}

        def connect(i, j):
            conns[(i, j)] = Connection(sets[i], queues[(i, j)].append)
            conns[(i, j)].open()

        for i in range(3):
            for j in range(3):
                if i != j:
                    connect(i, j)

        def pump(drop_p, rounds=15):
            for _ in range(rounds):
                moved = False
                for (i, j), q in queues.items():
                    while q:
                        msg = q.pop(0)
                        if rng.random() < drop_p:
                            continue
                        conns[(j, i)].receive_msg(msg)
                        moved = True
                if not moved:
                    break

        sets[0].set_doc("d", am.change(am.init("seed"),
                                       lambda d: d.__setitem__("x", 0)))
        for step in range(6):           # lossy editing period
            i = rng.randrange(3)
            cur = sets[i].get_doc("d")
            if cur is not None:
                sets[i].set_doc("d", am.change(
                    am.set_actor_id(cur, f"n{i}s{step}"),
                    lambda d: d.__setitem__(f"k{step}", i)))
            pump(drop_p=0.3, rounds=2)

        # recovery: reconnect every face, then drain losslessly
        for pair in list(conns):
            conns[pair].close()
            connect(*pair)
        for _ in range(5):
            pump(drop_p=0.0)
        states = [am.to_json(sets[i].get_doc("d")) for i in range(3)
                  if sets[i].get_doc("d") is not None]
        assert len(states) >= 2, f"seed {seed}: doc never spread"
        assert all(s == states[0] for s in states), \
            f"seed {seed}: diverged after reconnect: {states}"

"""Lock-striped ring-buffer flight recorder — the storage tier of
`automerge_tpu.obs`.

Design constraints (ISSUE 6, INTERNALS §11):

- **Bounded memory.** Records live in N_STRIPES independent ring buffers
  of `capacity` slots each; overflow overwrites the oldest record of the
  writer's stripe (the flight-recorder contract: the newest spans always
  survive). Worst-case footprint is ``n_stripes * capacity`` small
  tuples — ~tens of MB at the default 8 x 32768 even with per-record
  arg dicts.
- **No torn records.** A record is ONE tuple appended under its stripe's
  lock; readers only ever observe whole tuples. Stripes are selected by
  thread id, so the pipeline ring's worker thread and the caller thread
  write to different stripes and never contend on one lock in steady
  state (threads can hash-collide onto a stripe; the lock keeps that
  correct, just slower).
- **Snapshot without perturbing writers** (Jiffy's snapshot discipline,
  PAPERS.md): `snapshot()` copies each stripe's list under its lock —
  an O(capacity) slice copy, no global pause, writers blocked only for
  their own stripe's copy.
- **Counters survive wraparound.** Event/dispatch COUNTS aggregate in
  per-stripe dicts independent of the ring, so `metrics_snapshot()`
  totals are exact even after the ring dropped the oldest records.

This module is import-light on purpose (stdlib only): the engine imports
it on every process start, traced or not.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

# Record tuple layout (documented in INTERNALS §11; exported traces map it
# onto Chrome trace events):
#   (ts_ns, dur_ns, cat, name, tid, args)
# dur_ns >= 0  -> a completed span [ts_ns, ts_ns + dur_ns)
# dur_ns == -1 -> an instant event at ts_ns
# args: a small dict of payload fields (doc id, batch gen, counts...) or
# None. ts_ns is time.perf_counter_ns — monotonic within the process,
# comparable across threads.
EVENT_DUR = -1

TS, DUR, CAT, NAME, TID, ARGS = range(6)

#: Stripe count — a power of two so stripe selection is one mask op.
N_STRIPES = 8

#: Default ring capacity PER STRIPE (records). Override with
#: ``AMTPU_TRACE_CAPACITY`` (also per stripe) before `enable()`.
DEFAULT_CAPACITY = 32768


def default_capacity() -> int:
    try:
        cap = int(os.environ.get("AMTPU_TRACE_CAPACITY", "0"))
    except ValueError:
        cap = 0
    return cap if cap > 0 else DEFAULT_CAPACITY


class _Stripe:
    __slots__ = ("lock", "buf", "head", "counters")

    def __init__(self):
        self.lock = threading.Lock()
        self.buf: list = []      # ring storage (grows to capacity, then wraps)
        self.head = 0            # total records ever written to this stripe
        self.counters: dict = {}  # (cat, name) -> count (wrap-proof)


class FlightRecorder:
    """Bounded, lock-striped span/event store. One instance per enabled
    tracing session (module-level in `automerge_tpu.obs`)."""

    def __init__(self, capacity: Optional[int] = None,
                 n_stripes: int = N_STRIPES):
        if n_stripes < 1 or n_stripes & (n_stripes - 1):
            raise ValueError("n_stripes must be a power of two")
        self.capacity = max(16, capacity if capacity is not None
                            else default_capacity())
        self._mask = n_stripes - 1
        self._stripes = [_Stripe() for _ in range(n_stripes)]
        self.t0_ns = time.perf_counter_ns()   # session origin (export base)

    # -- write side (hot; callers already checked the enable flag) -------

    def emit(self, rec: tuple):
        """Append one whole record tuple to the writer thread's stripe."""
        s = self._stripes[threading.get_ident() & self._mask]
        with s.lock:
            if len(s.buf) < self.capacity:
                s.buf.append(rec)
            else:
                s.buf[s.head % self.capacity] = rec
            s.head += 1

    def bump(self, key: tuple, n: int = 1):
        """Aggregate a counter (exact across ring wraparound)."""
        s = self._stripes[threading.get_ident() & self._mask]
        with s.lock:
            s.counters[key] = s.counters.get(key, 0) + n

    # -- read side (never blocks writers globally) ------------------------

    def snapshot(self, since_ns: int = 0) -> list:
        """All retained records (oldest-first by timestamp), optionally
        only those starting at/after `since_ns`. Each stripe is copied
        under its own lock; the merge runs outside any lock."""
        out: list = []
        for s in self._stripes:
            with s.lock:
                if len(s.buf) < self.capacity:
                    part = list(s.buf)
                else:
                    cut = s.head % self.capacity
                    part = s.buf[cut:] + s.buf[:cut]
            out.extend(part)
        if since_ns:
            out = [r for r in out if r[TS] >= since_ns]
        out.sort(key=lambda r: r[TS])
        return out

    def counters(self) -> dict:
        """Merged counter totals: {(cat, name): count}."""
        out: dict = {}
        for s in self._stripes:
            with s.lock:
                items = list(s.counters.items())
            for k, v in items:
                out[k] = out.get(k, 0) + v
        return out

    @property
    def n_emitted(self) -> int:
        """Total records ever written (>= retained when wrapped)."""
        return sum(s.head for s in self._stripes)

    @property
    def n_retained(self) -> int:
        return sum(min(s.head, self.capacity) for s in self._stripes)

    def clear(self):
        for s in self._stripes:
            with s.lock:
                s.buf = []
                s.head = 0
                s.counters = {}


def span_totals(records, cat: Optional[str] = None) -> dict:
    """Aggregate spans by (cat, name): {key: {"count", "total_ns",
    "min_ns", "max_ns"}}. Events (dur == -1) are excluded. `cat` filters
    to one category."""
    out: dict = {}
    for r in records:
        if r[DUR] < 0 or (cat is not None and r[CAT] != cat):
            continue
        key = (r[CAT], r[NAME])
        agg = out.get(key)
        if agg is None:
            out[key] = {"count": 1, "total_ns": r[DUR],
                        "min_ns": r[DUR], "max_ns": r[DUR]}
        else:
            agg["count"] += 1
            agg["total_ns"] += r[DUR]
            if r[DUR] < agg["min_ns"]:
                agg["min_ns"] = r[DUR]
            if r[DUR] > agg["max_ns"]:
                agg["max_ns"] = r[DUR]
    return out


def span_seconds(records, cat: str, name: Optional[str] = None) -> float:
    """Total seconds of all spans in `cat` (optionally one `name`) — the
    bench serial-profile derivation: a term is the SUM of the recorded
    spans of its category, never whatever elapsed between two hand-placed
    perf_counter calls (the PR-5 attribution bug, made structural)."""
    total = 0
    for r in records:
        if (r[DUR] >= 0 and r[CAT] == cat
                and (name is None or r[NAME] == name)):
            total += r[DUR]
    return total / 1e9

"""Table CRDT tests — coverage mirrors /root/reference/test/table_test.js."""

import pytest

import automerge_tpu as am
from automerge_tpu import Table


def make_table():
    doc = am.change(am.init("actor-1"), lambda d: d.__setitem__("books", Table()))
    row_ids = {}

    def add(d):
        row_ids["ddia"] = d["books"].add({
            "authors": ["Kleppmann, Martin"],
            "title": "Designing Data-Intensive Applications",
            "isbn": "1449373321",
        })
    doc = am.change(doc, add)
    return doc, row_ids["ddia"]


class TestTable:
    def test_create_empty(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("books", Table()))
        assert doc["books"].count == 0
        assert doc["books"].ids == []

    def test_add_row_and_by_id(self):
        doc, row_id = make_table()
        row = doc["books"].by_id(row_id)
        assert row["title"] == "Designing Data-Intensive Applications"
        assert row["id"] == row_id
        assert doc["books"].count == 1

    def test_row_object_id_is_row_id(self):
        doc, row_id = make_table()
        assert am.get_object_id(doc["books"].by_id(row_id)) == row_id

    def test_rows_and_iteration(self):
        doc, row_id = make_table()
        assert [r["isbn"] for r in doc["books"]] == ["1449373321"]
        assert doc["books"].rows[0]["id"] == row_id

    def test_filter_find_map(self):
        doc, _ = make_table()
        books = doc["books"]
        assert books.filter(lambda r: r["isbn"] == "1449373321")[0]["title"].startswith("Designing")
        assert books.find(lambda r: False) is None
        assert books.map(lambda r: r["isbn"]) == ["1449373321"]

    def test_sort_by_column(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("t", Table()))

        def add_rows(d):
            d["t"].add({"k": "b", "n": 2})
            d["t"].add({"k": "a", "n": 3})
            d["t"].add({"k": "c", "n": 1})
        doc = am.change(doc, add_rows)
        assert [r["k"] for r in doc["t"].sort("k")] == ["a", "b", "c"]
        assert [r["n"] for r in doc["t"].sort("n")] == [1, 2, 3]

    def test_remove_row(self):
        doc, row_id = make_table()
        doc2 = am.change(doc, lambda d: d["t" if False else "books"].remove(row_id))
        assert doc2["books"].count == 0

    def test_remove_missing_row_raises(self):
        doc, _ = make_table()
        with pytest.raises(KeyError):
            am.change(doc, lambda d: d["books"].remove("no-such-row"))

    def test_update_row_field(self):
        doc, row_id = make_table()
        doc2 = am.change(doc, lambda d: d["books"].by_id(row_id).__setitem__("isbn", "1"))
        assert doc2["books"].by_id(row_id)["isbn"] == "1"

    def test_row_id_property_rejected(self):
        doc = am.change(am.init(), lambda d: d.__setitem__("t", Table()))
        with pytest.raises(TypeError, match='"id"'):
            am.change(doc, lambda d: d["t"].add({"id": "custom"}))

    def test_non_empty_table_assignment_rejected(self):
        doc, row_id = make_table()

        def reassign(d):
            d["other"] = Table()  # empty is fine
        am.change(doc, reassign)

    def test_concurrent_rows_merge(self):
        base = am.change(am.init("actor-1"), lambda d: d.__setitem__("t", Table()))
        other = am.merge(am.init("actor-2"), base)
        a = am.change(base, lambda d: d["t"].add({"k": "from-a"}))
        b = am.change(other, lambda d: d["t"].add({"k": "from-b"}))
        m1, m2 = am.merge(a, b), am.merge(b, a)
        assert m1["t"].count == m2["t"].count == 2
        assert sorted(r["k"] for r in m1["t"]) == ["from-a", "from-b"]

    def test_save_load(self):
        doc, row_id = make_table()
        loaded = am.load(am.save(doc), "actor-2")
        assert loaded["books"].by_id(row_id)["isbn"] == "1449373321"

    def test_to_json(self):
        doc, row_id = make_table()
        js = am.to_json(doc)
        assert js["books"][row_id]["isbn"] == "1449373321"

"""Device-resident map/counter CRDT document.

The map analogue of `DeviceTextDoc`: key registers live as padded columnar
tables in device memory and whole batches of changes merge per causally-ready
round in one jitted program (`ops/ingest.py:apply_map_round`). This replaces
the reference's per-op map reconciliation (`applyAssign` on map objects +
Immutable.js `byObject` maps, /root/reference/backend/op_set.js:196-258) with
scatter-based LWW resolution over interned key slots:

- keys intern to dense int32 slots (host dictionary; slot = register index)
- the device fast path resolves empty-register sets and same-actor
  overwrites at memory bandwidth; concurrent multi-writer rounds, deletes,
  counter increments, and pooled (non-inline-int) values flow through the
  shared host slow path (engine/base.py) with identical semantics to the
  oracle: winner = highest actor id, concurrent survivors are conflicts,
  `inc` folds into causally-visible counter values

`vmap`-style batching over many documents comes from the DocSet layer
stacking per-doc batches; each doc's round is one device call either way.
"""

from __future__ import annotations

import numpy as np

from .base import CausalDeviceDoc
from .columnar import MapChangeBatch


class DeviceMapDoc(CausalDeviceDoc):
    """One map object: interned keys -> LWW registers on device."""

    batch_type = MapChangeBatch

    def __init__(self, obj_id: str = "map", capacity: int = 256):
        from ..ops.ingest import bucket
        super().__init__(obj_id)
        self.key_table: list = []             # slot -> key string
        self._key_slot: dict = {}
        self._cap = bucket(max(capacity, 16))

    # ------------------------------------------------------------------
    # device state
    # ------------------------------------------------------------------

    def reserve(self, n: int):
        """Raise the capacity floor so upcoming applies jump straight to
        bucket(n) instead of growing through every intermediate bucket —
        each bucket is a distinct static shape, i.e. a fresh XLA compile
        (the am.load pathology; backend/device.py _distribute). Safe with
        live tables: the ingest kernel extends operands to out_cap
        (ops/ingest.py _ext)."""
        from ..ops.ingest import bucket
        self._cap = max(self._cap, bucket(max(n, 16)))

    def _ensure_dev(self) -> dict:
        self._check_device_alive()
        if self._dev is None:
            import jax.numpy as jnp
            cap = self._cap
            self._dev = {
                "value": jnp.zeros(cap, jnp.int32),
                "has_value": jnp.zeros(cap, bool),
                "win_actor": jnp.full(cap, -1, jnp.int32),
                "win_seq": jnp.zeros(cap, jnp.int32),
                "win_counter": jnp.zeros(cap, bool),
            }
        return self._dev

    def _mirrors(self) -> dict:
        if self._host is None:
            self._host = self._fetch_mirrors(
                ("value", "has_value", "win_counter"))
        return self._host

    def _remap_device(self, remap: np.ndarray):
        import jax.numpy as jnp
        from ..ops.ingest import remap_ranks
        dev = self._ensure_dev()
        self._count_dispatch(label="remap_ranks")
        dev["win_actor"] = remap_ranks(dev["win_actor"], jnp.asarray(remap))

    def _intern_keys(self, keys) -> np.ndarray:
        for k in keys:
            if k not in self._key_slot:
                self._key_slot[k] = len(self.key_table)
                self.key_table.append(k)
        return np.asarray([self._key_slot[k] for k in keys], np.int32)

    # ------------------------------------------------------------------
    # round ingestion
    # ------------------------------------------------------------------

    def _plan_map_round(self, b: MapChangeBatch, mask):
        """HOST planning of one causally-ready round of map ops: key
        interning + resolved op columns, zero device work. Returns None
        for an empty round; otherwise the dict both the solo `_ingest`
        dispatch and the stacked multi-object executor
        (engine/stacked.py) consume. `val64` keeps the unclipped values
        the host slow path needs (pool refs survive clipping anyway;
        plain int64 magnitudes do not)."""
        from ..ops.ingest import bucket

        kind = np.ascontiguousarray(b.op_kind[mask])
        n_ops = len(kind)
        if n_ops == 0:
            return None
        op_key = b.op_key[mask]
        val64 = b.op_value[mask]
        op_row = b.op_change[mask]

        key_map = self._intern_keys(b.key_table)   # batch kid -> global slot
        slot = key_map[op_key]
        row_actor_rank = np.asarray(
            [self._actor_rank[a] for a in b.actors], np.int32)
        row_seq = np.asarray(b.seqs, np.int32)
        return {
            "n_ops": n_ops, "kind": kind, "slot": slot,
            "value": np.clip(val64, -2**31, 2**31 - 1).astype(np.int32),
            "win_actor": row_actor_rank[op_row],
            "win_seq": row_seq[op_row], "val64": val64,
            "out_cap": max(bucket(len(self.key_table)), self._cap),
        }

    def _ingest(self, b: MapChangeBatch, mask):
        import jax.numpy as jnp
        from ..ops.ingest import apply_map_round, bucket

        p = self._plan_map_round(b, mask)
        if p is None:
            return
        n_ops = p["n_ops"]
        kind = p["kind"]
        out_cap = p["out_cap"]
        dev = self._ensure_dev()
        M = bucket(n_ops, 128)

        def padm(arr, fill, dtype=np.int32):
            out = np.full(M, fill, dtype)
            out[:n_ops] = arr
            return jnp.asarray(out)

        K = bucket(max(len(self.conflicts), 1), 64)
        conflict_slots = np.full(K, out_cap, np.int32)
        if self.conflicts:
            conflict_slots[: len(self.conflicts)] = list(self.conflicts)

        self._count_dispatch(label="apply_map_round")
        # exact h2d meter: the round's op columns (one int8 + four int32
        # M-padded arrays) + the conflict-slot vector
        self._count_h2d(M * (1 + 4 * 4) + K * 4)
        (value_n, has_n, wa_n, ws_n, wc_n, slow_info) = apply_map_round(
            dev["value"], dev["has_value"], dev["win_actor"],
            dev["win_seq"], dev["win_counter"],
            padm(kind, -1, np.int8), padm(p["slot"], out_cap),
            padm(p["value"], 0),
            padm(p["win_actor"], 0), padm(p["win_seq"], 0),
            jnp.asarray(conflict_slots), out_cap=out_cap)

        self._dev = {"value": value_n, "has_value": has_n, "win_actor": wa_n,
                     "win_seq": ws_n, "win_counter": wc_n}
        self._cap = out_cap
        self._host = None

        # one packed transfer: slow mask + slots + register state
        from .. import obs
        _ts = obs.now() if obs.ENABLED else 0
        # count the FULL padded buffer: that is what crosses the link —
        # the n_ops slice is a host-side view after the transfer
        info_full = np.asarray(slow_info)
        self._count_sync(label="slow_info_fetch",
                         dur_ns=(obs.now() - _ts) if _ts else 0,
                         d2h_bytes=info_full.nbytes)
        info = info_full[:, :n_ops]
        if info[0].any():
            idxs = np.nonzero(info[0])[0]
            self._apply_slow(
                b, info[1][idxs], kind[idxs], p["val64"][idxs],
                p["win_actor"][idxs], p["win_seq"][idxs],
                slot_cap=self._cap,
                reg_state=tuple(info[r][idxs] for r in range(2, 7)))

    # ------------------------------------------------------------------
    # accessors
    # ------------------------------------------------------------------

    def _decode(self, v: int):
        if v >= 0:
            return int(v)
        return self.value_pool[-v - 1]["value"]

    def to_dict(self) -> dict:
        h = self._mirrors()
        out = {}
        for key, slot in self._key_slot.items():
            if h["has_value"][slot]:
                out[key] = self._decode(int(h["value"][slot]))
        return out

    def get(self, key: str, default=None):
        slot = self._key_slot.get(key)
        if slot is None:
            return default
        h = self._mirrors()
        if not h["has_value"][slot]:
            return default
        return self._decode(int(h["value"][slot]))

    def conflicts_for(self, key: str):
        slot = self._key_slot.get(key)
        extras = self.conflicts.get(slot) if slot is not None else None
        if not extras:
            return None
        return {self.actor_table[op["actor_rank"]]: self._decode(op["value"])
                for op in extras}

    def __len__(self) -> int:
        h = self._mirrors()
        n = len(self.key_table)
        return int(h["has_value"][:n].sum())

    def __contains__(self, key: str) -> bool:
        slot = self._key_slot.get(key)
        if slot is None:
            return False
        return bool(self._mirrors()["has_value"][slot])

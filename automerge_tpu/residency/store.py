"""The warm/cold bundle store behind the residency manager.

A demoted doc's entire state is its PR-3 AMTPUCKPT1 checkpoint bundle
(versioned manifest + per-array SHA-256 — `checkpoint/engine_codec.py`):
the spill format IS the checkpoint format, so a spilled doc restores by
pure h2d table staging (`ShardLane.adopt` -> `restore_engine`), never by
replay, and every page-in re-verifies the integrity hashes for free.

Two tiers live here:

- **warm**: bundle bytes in host memory (`dict`), the fast page-in tier;
- **cold**: bundle bytes aged to one file per doc under ``spill_dir``
  (atomic ``os.replace`` writes; file names are sha1(doc_id) so a doc id
  is never a path traversal). With no ``spill_dir`` configured the cold
  tier is disabled and warm bundles simply stay warm.

The store never decides WHEN to demote/age — that is the manager's
policy — it only guarantees nothing is ever lost between tiers: a doc is
in exactly one of {warm, cold} or absent, and the accounting surface
(`tiers()`, byte gauges) is exact.
"""

from __future__ import annotations

import hashlib
import os

from ..engine import learned_index


class BundleStore:
    """Host-side (warm) + disk (cold) checkpoint-bundle store."""

    def __init__(self, spill_dir: str = None):
        self.spill_dir = spill_dir
        self._warm: dict = {}           # doc_id -> bundle bytes
        self._cold: dict = {}           # doc_id -> (path, nbytes)
        self._gen = 0                   # membership generation: bumps on
        self._learned = None            # put/pop; (gen, ids, model pair)
        self.stats = {"puts": 0, "gets": 0, "ages": 0, "loads": 0,
                      "peak_warm_bytes": 0, "peak_cold_bytes": 0}

    # -- tier membership -----------------------------------------------

    def __contains__(self, doc_id: str) -> bool:
        return doc_id in self._warm or doc_id in self._cold

    def member_mask(self, doc_ids):
        """Batched stored-membership of ``doc_ids`` — ONE learned/packed
        position probe over the store's sorted id table (the
        "residency_clock" site) instead of a per-doc ``in`` probe each,
        with the full-key equality gate guaranteeing exactness. The
        table + model are cached per membership generation (put/pop
        bumps — the same token discipline as the interning-generation
        retrain trigger). Returns a bool array aligned to ``doc_ids``,
        or None when the site must take the exact path (flag off,
        demoted, unpackable ids)."""
        if not learned_index.site_enabled("residency_clock"):
            return None
        ent = self._learned
        if ent is None or ent[0] != self._gen:
            ids = sorted([*self._warm, *self._cold])
            tk = learned_index.pack_str_keys(ids)
            pair = None
            if tk is not None and (len(tk) < 2
                                   or bool((tk[1:] > tk[:-1]).all())):
                pair = (tk, learned_index.fit_model(tk, "residency_clock"))
            ent = (self._gen, ids, pair)
            self._learned = ent
        _gen, ids, pair = ent
        if pair is None:
            return None
        got = learned_index.actor_positions(
            ids, doc_ids, "residency_clock", model=pair)
        if got is None:
            return None
        return got[1]

    def tier(self, doc_id: str):
        if doc_id in self._warm:
            return "warm"
        if doc_id in self._cold:
            return "cold"
        return None

    def warm_ids(self) -> list:
        return sorted(self._warm)

    def cold_ids(self) -> list:
        return sorted(self._cold)

    @property
    def warm_bytes(self) -> int:
        return sum(len(b) for b in self._warm.values())

    @property
    def cold_bytes(self) -> int:
        return sum(n for _, n in self._cold.values())

    # -- write side ----------------------------------------------------

    def put(self, doc_id: str, bundle: bytes):
        """Admit a freshly demoted doc to the warm tier (a re-demote
        overwrites: the newest bundle is the doc's only truth)."""
        self._cold.pop(doc_id, None)
        self._warm[doc_id] = bundle
        self._gen += 1
        self.stats["puts"] += 1
        wb = self.warm_bytes
        if wb > self.stats["peak_warm_bytes"]:
            self.stats["peak_warm_bytes"] = wb

    def _cold_path(self, doc_id: str) -> str:
        digest = hashlib.sha1(doc_id.encode()).hexdigest()
        return os.path.join(self.spill_dir, f"{digest}.amtpuckpt")

    def age(self, doc_id: str) -> bool:
        """Warm -> cold: write the bundle to its spill file (atomic
        tmp + replace) and drop the host copy. No-op (False) without a
        spill_dir or when the doc is not warm."""
        if self.spill_dir is None or doc_id not in self._warm:
            return False
        os.makedirs(self.spill_dir, exist_ok=True)
        path = self._cold_path(doc_id)
        bundle = self._warm[doc_id]
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(bundle)
        os.replace(tmp, path)
        del self._warm[doc_id]
        self._cold[doc_id] = (path, len(bundle))
        self.stats["ages"] += 1
        cb = self.cold_bytes
        if cb > self.stats["peak_cold_bytes"]:
            self.stats["peak_cold_bytes"] = cb
        return True

    # -- read side -----------------------------------------------------

    def peek(self, doc_id: str):
        """The doc's bundle bytes without changing its tier (the
        capture/read path: a demoted doc's checkpoint IS its stored
        bundle, byte-identical to a live capture). None when absent."""
        bundle = self._warm.get(doc_id)
        if bundle is not None:
            return bundle
        entry = self._cold.get(doc_id)
        if entry is None:
            return None
        path, _nbytes = entry
        with open(path, "rb") as fh:
            return fh.read()

    def pop(self, doc_id: str):
        """Remove and return the bundle (the page-in path). A cold hit
        counts a disk load and deletes the spill file — the doc is
        becoming device-resident again; the bundle in hand is the only
        copy by design (one tier at a time)."""
        bundle = self._warm.pop(doc_id, None)
        if bundle is not None:
            self._gen += 1
            self.stats["gets"] += 1
            return bundle
        entry = self._cold.pop(doc_id, None)
        if entry is None:
            return None
        self._gen += 1
        path, _nbytes = entry
        with open(path, "rb") as fh:
            bundle = fh.read()
        try:
            os.remove(path)
        except OSError:
            pass
        self.stats["gets"] += 1
        self.stats["loads"] += 1
        return bundle

    def tiers(self) -> dict:
        """The full accounting surface: every stored doc named in its
        tier, with exact byte totals."""
        return {"warm": self.warm_ids(), "cold": self.cold_ids(),
                "warm_bytes": self.warm_bytes,
                "cold_bytes": self.cold_bytes}

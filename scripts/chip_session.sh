#!/bin/bash
# One-shot TPU chip session: runs every measurement this round still needs,
# in priority order, appending to scripts/chip_session.log. Safe to re-run;
# each step has its own timeout so a wedged tunnel can't eat the session.
set -u
cd "$(dirname "$0")/.."
LOG=scripts/chip_session.log

# single-flight guard: the chip admits ONE client; a second concurrent
# session would wedge both (the probe loop may auto-launch this script)
exec 9> /tmp/chip_session.lock
flock -n 9 || { echo "chip session already running; exiting" >> "$LOG"; exit 5; }

echo "=== chip session $(date -u +%FT%TZ) ===" >> "$LOG"

run() {
  local name="$1"; shift
  echo "--- $name ($(date -u +%T)) ---" >> "$LOG"
  timeout "$1" "${@:2}" >> "$LOG" 2>&1
  echo "--- $name rc=$? ---" >> "$LOG"
}

# shared strict probe: proves a NON-CPU device actually computes — a
# silent CPU fallback would run the whole measurement queue off-chip
run "probe"            120 python scripts/probe_device.py
grep -q "rc=0" <(tail -1 "$LOG") || { echo "tunnel down, aborting" >> "$LOG"; exit 3; }
export AMTPU_SKIP_PREFLIGHT=1   # this session IS the parent probe

AUTOMERGE_TPU_TESTS_ON_TPU=1 \
  run "tpu_smoke"      900 python -m pytest tests/test_segments.py tests/test_engine_parity.py tests/test_fast_local.py -q
grep -q "rc=0" <(tail -1 "$LOG") || { echo "on-chip smoke FAILED, not recording benchmarks" >> "$LOG"; exit 4; }
run "bench"            900 python bench.py
run "planned_ab"       900 python profile_bench.py --planned
run "trace"            600 python profile_bench.py --trace
run "pallas_ab"        900 python profile_bench.py --pallas
run "configs_record"  3600 python -m benchmarks.run_all --record "${AMTPU_ROUND:-5}"
echo "=== chip session done $(date -u +%T) ===" >> "$LOG"

"""Host-side elemId -> device-slot index, compressed as counter ranges.

The reference resolves elemId references through per-object Immutable.js maps
(`_insertion`, /root/reference/backend/op_set.js:95-98,461-470). The device
engine instead keeps element *tables* on the TPU and resolves references on
the host, where the op columns originate anyway. Two facts make this cheap:

- elemIds minted by one actor have consecutive counters within a typing run,
  and runs land in consecutive device slots, so the index stores *ranges*
  ((actor, ctr0) .. +len -> slot0 .. +len), not individual elements;
- lookups are numpy ``searchsorted`` over packed range starts — C-speed
  binary search, no device round trip, no int64 emulation on the TPU (int64
  sorts/searches run emulated and severalfold slower than int32 on v5e;
  design assumption, docs/MEASUREMENTS.md).

Keys pack as (actor_rank << 32 | ctr); counters stay < 2^31 so keys within a
range are consecutive integers and slot arithmetic is a subtraction.

Two index structures implement the same contract (INTERNALS §16.2):

- :class:`BatchRangeIndex` (default) — Jiffy-style batch-update tiers: a
  round's minted ranges land as ONE immutable sorted run appended to a
  small tier list, with amortized size-doubling compaction; every
  instance is persistent (``merge``/``remap_actors`` return NEW
  indexes, nothing published is ever written again), so readers —
  checkpoint ``grab()``, pull paths, the stacked gather — take
  zero-coordination O(1) snapshots (``snapshot()`` is ``self``) that can
  never observe a torn merge. Per-round cost is O(K log K + K log R)
  instead of the sorted-insert array's O(R) whole-array copy; the index
  grows with document lifetime, the round's ranges do not.
- :class:`SortedInsertIndex` — the PR-2 sorted-insert array, kept
  verbatim behind ``AMTPU_BATCH_INDEX=0`` as the parity comparator
  (tests/test_batch_index.py pins lookup/merge/flatten byte-identity).

Both coalesce key- and slot-contiguous neighbors in their flattened view,
so checkpoint bundles (``idx_starts``/``idx_lens``/``idx_slots``) are
byte-identical across the flag.
"""

from __future__ import annotations

import os

import numpy as np

from .._common import check_int32_envelope
from .. import obs
from . import learned_index as _learned

#: Process-wide bulk-merge accounting: the cfg12t budget — one bulk merge
#: per doc per round, never one insert per range — is asserted against
#: these counters (engine/stacked.assert_round_budget, bench.py cfg12t).
MERGE_STATS = {"bulk_merges": 0, "ranges_inserted": 0, "compactions": 0}

#: Below this many ranges in the base run, ``lookup_learned`` skips the
#: model-fit attempt outright: a binary search over a handful of ranges
#: is already cheaper than any model's fixed probe cost.
_MIN_MODEL_RANGES = 8


def merge_stats_snapshot() -> dict:
    return dict(MERGE_STATS)


def batch_index_enabled() -> bool:
    """The batch-update tiered index (INTERNALS §16.2) is the default;
    the legacy sorted-insert array stays available as the parity
    comparator behind ``AMTPU_BATCH_INDEX=0`` (read per call so tests
    can pin either structure)."""
    return os.environ.get("AMTPU_BATCH_INDEX", "1") != "0"


def new_index():
    """A fresh empty index of the configured structure."""
    return BatchRangeIndex() if batch_index_enabled() \
        else SortedInsertIndex()


def index_from_rows(starts, lens, slots):
    """Rebuild an index of the configured structure from flattened rows
    (checkpoint restore; rows are trusted sorted + disjoint)."""
    cls = BatchRangeIndex if batch_index_enabled() else SortedInsertIndex
    return cls.from_rows(starts, lens, slots)


def pack_keys(actor: np.ndarray, ctr: np.ndarray) -> np.ndarray:
    """(actor_rank, ctr) -> packed int64 key. Loud on envelope overflow:
    a ctr or rank past 2^31-1 (or negative) would corrupt the packing —
    adjacent keys would collide or reorder — instead of failing, so the
    guard raises OverflowError before any key escapes (VERDICT r5 item 3;
    tests/test_int32_guards.py)."""
    check_int32_envelope("elemId counter", ctr)
    check_int32_envelope("actor rank", actor)
    return (actor.astype(np.int64) << 32) | ctr.astype(np.int64)


def unpack_key(key: int) -> tuple:
    """packed key -> (actor_rank, ctr)."""
    return key >> 32, key & 0xFFFFFFFF


class DuplicateElemId(ValueError):
    """An inserted elemId overlaps an existing one (`key` is packed).

    The engine decodes `key` against its actor table for the user-facing
    message (the reference's duplicate-insertion inconsistency check,
    op_set.js applyInsert)."""

    def __init__(self, key: int):
        super().__init__("Duplicate list element ID")
        self.key = key


def _sort_new(starts, lens, slots):
    """Sort one merge call's ranges by start (stable) and validate the
    within-call overlap; int64 working copies."""
    new_starts = np.asarray(starts, np.int64)
    new_lens = np.asarray(lens, np.int64)
    new_slots = np.asarray(slots, np.int64)
    if len(new_starts) > 1:
        order = np.argsort(new_starts, kind="stable")
        new_starts = new_starts[order]
        new_lens = new_lens[order]
        new_slots = new_slots[order]
        ends = new_starts + new_lens
        bad = np.flatnonzero(ends[:-1] > new_starts[1:])
        if len(bad):
            raise DuplicateElemId(int(new_starts[bad[0] + 1]))
    return new_starts, new_lens, new_slots


def _coalesce(starts, lens, slots):
    """Coalesce key- AND slot-contiguous neighbors of one sorted,
    non-overlapping run (the legacy per-merge pass, shared so the two
    structures' flattened views are byte-identical)."""
    if len(starts) > 1:
        ends = starts + lens
        joined = (ends[:-1] == starts[1:]) & \
                 (slots[:-1] + lens[:-1] == slots[1:])
        if joined.any():
            head = np.concatenate([[True], ~joined])
            group = np.cumsum(head) - 1
            n = int(group[-1]) + 1
            g_start = starts[head]
            g_slot = slots[head]
            g_len = np.zeros(n, np.int64)
            np.add.at(g_len, group, lens)
            starts, lens, slots = g_start, g_len, g_slot
    return starts, lens, slots


def _merge_runs(a, b):
    """Merge two sorted disjoint runs into one (stable by start; equal
    starts cannot occur — runs are key-disjoint), coalescing neighbors."""
    starts = np.concatenate([a[0], b[0]])
    lens = np.concatenate([a[1], b[1]])
    slots = np.concatenate([a[2], b[2]])
    order = np.argsort(starts, kind="stable")
    return _coalesce(starts[order], lens[order], slots[order])


class SortedInsertIndex:
    """Sorted, coalesced (key range -> slot range) map — the legacy
    sorted-insert array (parity comparator, ``AMTPU_BATCH_INDEX=0``).

    Persistent like its replacement: ``merge`` and ``remap_actors``
    return NEW indexes and published array attributes are only ever
    rebound, never written — so ``snapshot()`` is a cheap consistent
    view here too."""

    __slots__ = ("starts", "lens", "slots", "_slot_view")

    def __init__(self):
        self.starts = np.empty(0, np.int64)   # packed first key of each range
        self.lens = np.empty(0, np.int64)
        self.slots = np.empty(0, np.int64)    # device slot of the first key
        self._slot_view = None                # lazy slot-sorted view

    @classmethod
    def from_rows(cls, starts, lens, slots) -> "SortedInsertIndex":
        out = cls()
        out.starts = np.asarray(starts, np.int64)
        out.lens = np.asarray(lens, np.int64)
        out.slots = np.asarray(slots, np.int64)
        return out

    @property
    def n_ranges(self) -> int:
        return len(self.starts)

    def rows(self) -> tuple:
        """Flattened (starts, lens, slots) view (checkpoint encode)."""
        return self.starts, self.lens, self.slots

    def snapshot(self) -> "SortedInsertIndex":
        """A consistent read view: array refs are shared (every mutation
        rebinds, so a snapshot can never observe a torn merge)."""
        out = SortedInsertIndex()
        out.starts, out.lens, out.slots = self.starts, self.lens, self.slots
        return out

    def merge(self, starts: np.ndarray, lens: np.ndarray,
              slots: np.ndarray) -> "SortedInsertIndex":
        """Return a new index with the ranges inserted (the caller commits it
        only after every other validity check passes, so a raising batch
        leaves the document untouched). Raises ValueError on any key overlap
        (the reference's duplicate-elemId inconsistency, op_set.js
        applyInsert)."""
        if len(starts) == 0:
            return self
        _t0 = obs.now() if obs.ENABLED else 0
        # sort only the NEW ranges (K log K), then place them into the
        # already-sorted index with one searchsorted + insert (O(R + K))
        # instead of re-argsorting all R + K ranges per round — the index
        # grows with document lifetime, the round's minted ranges do not.
        # Equal-start collisions order new-before-old; both orders raise
        # DuplicateElemId below (every range has len >= 1).
        new_starts = starts.astype(np.int64)
        new_lens = lens.astype(np.int64)
        new_slots = slots.astype(np.int64)
        if len(new_starts) > 1:
            order = np.argsort(new_starts, kind="stable")
            new_starts = new_starts[order]
            new_lens = new_lens[order]
            new_slots = new_slots[order]
        if self.n_ranges == 0:
            starts, lens, slots = new_starts, new_lens, new_slots
        else:
            pos = np.searchsorted(self.starts, new_starts, side="left")
            starts = np.insert(self.starts, pos, new_starts)
            lens = np.insert(self.lens, pos, new_lens)
            slots = np.insert(self.slots, pos, new_slots)
        ends = starts + lens
        if len(starts) > 1:
            bad = np.flatnonzero(ends[:-1] > starts[1:])
            if len(bad):
                raise DuplicateElemId(int(starts[bad[0] + 1]))
        # count AFTER validation (as the batch structure does), so the
        # process-wide accounting agrees across the flag on raising merges
        MERGE_STATS["bulk_merges"] += 1
        MERGE_STATS["ranges_inserted"] += len(new_starts)
        # coalesce key- and slot-contiguous neighbors to keep the index small
        starts, lens, slots = _coalesce(starts, lens, slots)
        out = SortedInsertIndex()
        out.starts, out.lens, out.slots = starts, lens, slots
        if obs.ENABLED:
            obs.span("plan", "index_merge", _t0, args={
                "structure": "sorted_insert", "n_new": len(new_starts),
                "n_ranges": len(starts)})
        return out

    def lookup(self, keys: np.ndarray):
        """-> (slots int64, found bool) for packed query keys."""
        if self.n_ranges == 0:
            return (np.zeros(len(keys), np.int64),
                    np.zeros(len(keys), bool))
        pos = np.searchsorted(self.starts, keys, side="right") - 1
        safe = np.clip(pos, 0, None)
        found = (pos >= 0) & (keys < self.starts[safe] + self.lens[safe])
        slot = np.where(found, self.slots[safe] + (keys - self.starts[safe]), 0)
        return slot, found

    def slot_to_key(self, slots: np.ndarray):
        """Reverse lookup: device slots -> (actor_rank, ctr) of the element
        occupying each slot. Every live slot >= 1 is covered (each was
        registered when its insert was planned); raises on a slot outside
        every range. The slot-sorted view is cached — instances are
        immutable after construction."""
        view = self._slot_view
        if view is None:
            order = np.argsort(self.slots, kind="stable")
            view = (self.slots[order], self.lens[order], self.starts[order])
            self._slot_view = view
        return _slot_to_key(view, slots)

    def remap_actors(self, remap: np.ndarray) -> "SortedInsertIndex":
        """Re-rank the actor halves of the keys after interning inserted a
        new actor id below existing ones (rank order == lex order).
        Returns the remapped index (pure — the receiver is unchanged, so
        outstanding snapshots stay valid)."""
        if self.n_ranges == 0:
            return self
        actor = (self.starts >> 32).astype(np.int64)
        ctr = self.starts & 0xFFFFFFFF
        starts = (remap[actor].astype(np.int64) << 32) | ctr
        order = np.argsort(starts, kind="stable")
        out = SortedInsertIndex()
        out.starts = starts[order]
        out.lens = self.lens[order]
        out.slots = self.slots[order]
        return out


def _slot_to_key(view, slots):
    """Shared reverse-lookup body over a (slots, lens, starts) slot-sorted
    view (both index structures)."""
    s_slots, s_lens, s_starts = view
    slots = np.asarray(slots, np.int64)
    pos = np.searchsorted(s_slots, slots, side="right") - 1
    safe = np.clip(pos, 0, None)
    ok = (pos >= 0) & (slots < s_slots[safe] + s_lens[safe])
    if not ok.all():
        raise KeyError(
            f"slot {int(slots[np.flatnonzero(~ok)[0]])} not in index")
    key = s_starts[safe] + (slots - s_slots[safe])
    return key >> 32, key & 0xFFFFFFFF


class BatchRangeIndex:
    """Tiered batch-update range index with O(1) persistent snapshots.

    Jiffy's batch-update + O(1)-snapshot discipline (PAPERS.md) applied
    to the range map: a ``merge`` call lands the whole round's minted
    ranges as ONE immutable sorted run appended to a small tier tuple,
    validated against the existing tiers by binary-search probes
    (O(K log K + K·T·log R), T = tier count) — never by rewriting the
    resident O(R) array. Amortized size-doubling compaction (merge the
    newest run into its predecessor while it is at least as long) bounds
    the tier count at O(log R) and total compaction work at O(R log R)
    over a document's lifetime.

    Persistence is the memory model: every ``merge``/``remap_actors``
    returns a NEW index whose runs are frozen numpy arrays shared with
    the parent where unchanged; NOTHING reachable from a published index
    is ever written again. ``snapshot()`` is therefore ``self`` — a
    checkpoint grab, a pull, or the stacked gather can take it with zero
    coordination while another thread merges, and can never observe a
    torn state (tests/test_batch_index.py pins this under 8 threads).
    """

    __slots__ = ("_runs", "n_ranges", "_flat", "_slot_view", "_model")

    _COMPACT_TIERS = 12   # hard lid on tier count (lookup cost bound);
    # the doubling rule keeps real documents far below it

    def __init__(self):
        self._runs = ()        # tuple of (starts, lens, slots) sorted runs
        self.n_ranges = 0      # total ranges across runs (pre-coalesce)
        self._flat = None      # lazy flattened+coalesced view
        self._slot_view = None
        self._model = None     # lazy learned model over the base run;
        # inherited across merges while runs[0] is identity-preserved
        # (engine/learned_index.py; the exact `lookup` never consults it)

    @classmethod
    def from_rows(cls, starts, lens, slots) -> "BatchRangeIndex":
        out = cls()
        run = (np.asarray(starts, np.int64), np.asarray(lens, np.int64),
               np.asarray(slots, np.int64))
        if len(run[0]):
            out._runs = (run,)
            out.n_ranges = len(run[0])
            out._flat = run
        return out

    # -- flattened view (checkpoint encode, parity with the legacy) -----

    def _flatten(self) -> tuple:
        flat = self._flat
        if flat is None:
            if not self._runs:
                flat = (np.empty(0, np.int64), np.empty(0, np.int64),
                        np.empty(0, np.int64))
            else:
                flat = self._runs[0]
                for run in self._runs[1:]:
                    flat = _merge_runs(flat, run)
            self._flat = flat
        return flat

    @property
    def starts(self) -> np.ndarray:
        return self._flatten()[0]

    @property
    def lens(self) -> np.ndarray:
        return self._flatten()[1]

    @property
    def slots(self) -> np.ndarray:
        return self._flatten()[2]

    def rows(self) -> tuple:
        """Flattened (starts, lens, slots) view (checkpoint encode);
        byte-identical to the legacy structure's arrays."""
        return self._flatten()

    def snapshot(self) -> "BatchRangeIndex":
        """O(1), zero-coordination: the index is persistent, so the
        instance IS its own immutable snapshot."""
        return self

    # -- batch update ----------------------------------------------------

    def _check_overlap(self, new_starts, new_lens):
        """Raise DuplicateElemId when any new range overlaps a resident
        one. Probe-based (O(K log R) per tier); the offending key matches
        the legacy sorted-insert report: the later range's start in the
        merged order (new-before-old on equal starts, so an exact
        collision reports the OLD start — both carry the same key half
        anyway)."""
        new_ends = new_starts + new_lens
        worst = None
        for starts, lens, _slots in self._runs:
            # (a) a new range starting inside a resident range
            pos = np.searchsorted(starts, new_starts, side="right") - 1
            safe = np.clip(pos, 0, None)
            inside = (pos >= 0) & (new_starts < starts[safe] + lens[safe])
            if inside.any():
                k = int(new_starts[np.flatnonzero(inside)[0]])
                worst = k if worst is None else min(worst, k)
            # (b) a resident range starting inside a new range (strictly
            # after its start — case (a) covered equality)
            lo = np.searchsorted(starts, new_starts, side="right")
            safe = np.clip(lo, 0, len(starts) - 1)
            hit = (lo < len(starts)) & (starts[safe] < new_ends)
            if hit.any():
                k = int(starts[safe[np.flatnonzero(hit)[0]]])
                worst = k if worst is None else min(worst, k)
        if worst is not None:
            raise DuplicateElemId(worst)

    def merge(self, starts: np.ndarray, lens: np.ndarray,
              slots: np.ndarray) -> "BatchRangeIndex":
        """One bulk batch-update: the whole round's ranges land as one
        immutable run. Returns the NEW index (persistent); raises
        DuplicateElemId on any key overlap, leaving every published
        index untouched."""
        if len(starts) == 0:
            return self
        _t0 = obs.now() if obs.ENABLED else 0
        new_run = _sort_new(starts, lens, slots)
        self._check_overlap(new_run[0], new_run[1])
        MERGE_STATS["bulk_merges"] += 1
        MERGE_STATS["ranges_inserted"] += len(new_run[0])
        runs = list(self._runs)
        runs.append(_coalesce(*new_run))
        # amortized doubling compaction: merge the newest run downward
        # while it has grown at least as long as its predecessor
        while len(runs) > 1 and (
                len(runs[-1][0]) >= len(runs[-2][0])
                or len(runs) > self._COMPACT_TIERS):
            b = runs.pop()
            a = runs.pop()
            runs.append(_merge_runs(a, b))
            MERGE_STATS["compactions"] += 1
        for run in runs:
            for arr in run:
                arr.setflags(write=False)
        out = BatchRangeIndex()
        out._runs = tuple(runs)
        out.n_ranges = sum(len(r[0]) for r in runs)
        if len(runs) == 1:
            out._flat = runs[0]
        # the learned base-run model survives every merge that leaves
        # runs[0] untouched (the common case under doubling compaction);
        # a compaction that reaches the base invalidates it — the next
        # learned probe refits (counted on the "range_index" site)
        if self._runs and runs[0][0] is self._runs[0][0]:
            out._model = self._model
        if obs.ENABLED:
            obs.span("plan", "index_merge", _t0, args={
                "structure": "batch_tiers", "n_new": len(new_run[0]),
                "n_tiers": len(runs), "n_ranges": out.n_ranges})
        return out

    # -- reads -----------------------------------------------------------

    def lookup(self, keys: np.ndarray):
        """-> (slots int64, found bool) for packed query keys. One
        binary-search pass per tier; a key lives in at most one tier
        (ranges are globally disjoint), so the per-tier hits combine by
        masked select."""
        n = len(keys)
        slot = np.zeros(n, np.int64)
        found = np.zeros(n, bool)
        for starts, lens, slots_r in self._runs:
            pos = np.searchsorted(starts, keys, side="right") - 1
            safe = np.clip(pos, 0, None)
            hit = (pos >= 0) & (keys < starts[safe] + lens[safe])
            if hit.any():
                slot = np.where(hit, slots_r[safe] + (keys - starts[safe]),
                                slot)
                found |= hit
        return slot, found

    def scalar_affine(self, keys: np.ndarray):
        """The ε=0 degenerate model, evaluated in scalars: when the
        index has coalesced to ONE affine range (append-only steady
        state) and the query column is narrower than vector width,
        numpy's per-call fixed cost exceeds the arithmetic — the model
        evaluation is three int ops per key. Returns (slots, found)
        python lists, or None when the index is not a single range
        (caller falls through to the vectorized probe)."""
        runs = self._runs
        if len(runs) != 1 or len(runs[0][0]) != 1:
            return None
        starts, lens, slots_r = runs[0]
        s0 = int(starts[0])
        l0 = int(lens[0])
        z0 = int(slots_r[0])
        slots = []
        found = []
        for k in keys.tolist():
            off = k - s0
            hit = 0 <= off < l0
            found.append(hit)
            slots.append(z0 + off if hit else 0)
        _learned.RANGE_SITE.note_hits(len(slots))
        return slots, found

    def lookup_learned(self, keys: np.ndarray):
        """``lookup`` with the base-run probe routed through the learned
        position model (ISSUE 19): exact same (slots, found) — the model
        predicts the range position ± ε and the windowed verify makes it
        exact, with counted fallback on miss. Tail tiers (small, freshly
        merged runs) probe exactly; the base run is where the document's
        lifetime of ranges lives, so it is where the binary search
        depth was. Callers gate on ``learned_index.site_enabled``."""
        from . import learned_index as LI
        runs = self._runs
        n = len(keys)
        if len(runs) == 1:
            starts, lens, slots_r = runs[0]
            if len(starts) == 1:
                # the ε=0 degenerate model: an append-only document's
                # index coalesces to ONE affine range (slot = key −
                # start + slot0), so predict + verify collapses to a
                # single window compare — this is the steady state the
                # RocksDB learned-index result predicts for
                # append-mostly key distributions, and the hot shape of
                # the serving bench
                off = keys - starts[0]
                hit = (off >= 0) & (off < lens[0])
                _learned.RANGE_SITE.note(n, 0)
                return np.where(hit, slots_r[0] + off, 0), hit
        slot = np.zeros(n, np.int64)
        found = np.zeros(n, bool)
        first = True
        for starts, lens, slots_r in runs:
            if first:
                first = False
                if len(starts) >= _MIN_MODEL_RANGES:
                    ent = self._model
                    if ent is None or ent[0] is not starts:
                        # (source array, model | None): a refused fit is
                        # cached too, not re-attempted per probe
                        ent = (starts,
                               _learned.fit_model(starts, "range_index"))
                        self._model = ent
                    m = ent[1]
                else:
                    m = None
                if m is not None:
                    pos = m.searchsorted(keys, side="right") - 1
                else:
                    pos = np.searchsorted(starts, keys, side="right") - 1
            else:
                pos = np.searchsorted(starts, keys, side="right") - 1
            safe = np.clip(pos, 0, None)
            hit = (pos >= 0) & (keys < starts[safe] + lens[safe])
            if hit.any():
                slot = np.where(hit, slots_r[safe] + (keys - starts[safe]),
                                slot)
                found |= hit
        return slot, found

    def slot_to_key(self, slots: np.ndarray):
        """Reverse lookup over the flattened slot-sorted view (cached —
        instances are immutable)."""
        view = self._slot_view
        if view is None:
            f_starts, f_lens, f_slots = self._flatten()
            order = np.argsort(f_slots, kind="stable")
            view = (f_slots[order], f_lens[order], f_starts[order])
            self._slot_view = view
        return _slot_to_key(view, slots)

    def remap_actors(self, remap: np.ndarray) -> "BatchRangeIndex":
        """Re-rank the actor halves after an interning order change.
        Pure: returns a NEW index; the receiver (and every outstanding
        snapshot of it) is untouched."""
        if not self._runs:
            return self
        runs = []
        for starts, lens, slots_r in self._runs:
            actor = (starts >> 32).astype(np.int64)
            ctr = starts & 0xFFFFFFFF
            new_starts = (remap[actor].astype(np.int64) << 32) | ctr
            order = np.argsort(new_starts, kind="stable")
            run = (new_starts[order], lens[order], slots_r[order])
            for arr in run:
                arr.setflags(write=False)
            runs.append(run)
        out = BatchRangeIndex()
        out._runs = tuple(runs)
        out.n_ranges = self.n_ranges
        return out


#: Default structure under the configured flag — the name the engine and
#: annotations use. Constructions in engine code go through
#: :func:`new_index` so the flag is honored per document.
ElemRangeIndex = BatchRangeIndex

"""Device kernels. Everything here is int32/int8/bool by design: the TPU
emulates int64 (measured 10-30x slower sorts/searches on v5e), so 64-bit
packed elemId keys live exclusively on the host (engine/host_index.py)."""

from .linearize import rga_linearize  # noqa: F401
from .scan import segment_starts, visible_index  # noqa: F401
from .scan_pallas import fused_segment_scans  # noqa: F401

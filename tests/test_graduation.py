"""Graduation is provably unreachable for well-formed documents.

Round-3 widened `_in_scope` (backend/device.py) to accept every well-formed
op shape — nested maps/lists/tables/text, links, counters, undo/redo — so
the graduation escape hatch should fire ONLY for malformed deliveries
(unknown op actions). This file pins that contract:

- a property fuzz drives random arbitrarily-nested histories through the
  full public API and asserts ``GRADUATION_STATS == {}`` at the end (the
  device tier served everything);
- one test documents the single remaining trigger (an op whose action the
  wire schema does not define) and that behavior is still correct after
  graduating — a performance cliff, never a behavior change.
"""

import random

import automerge_tpu as am
from automerge_tpu import Table, Text
from automerge_tpu import frontend as Frontend
from automerge_tpu.backend import device as device_backend


def _random_value(rng, depth):
    r = rng.random()
    if depth > 2 or r < 0.4:
        return rng.choice([1, "s", True, None, 3.5])
    if r < 0.55:
        return {rng.choice("pq"): _random_value(rng, depth + 1)}
    if r < 0.7:
        return [_random_value(rng, depth + 1)
                for _ in range(rng.randrange(1, 3))]
    if r < 0.8:
        return Text(rng.choice(["", "ab", "xyz"]))
    if r < 0.9:
        return am.Counter(rng.randrange(5))
    return Table()


def _containers(doc):
    """Every mutable container reachable from the root, with its path."""
    out = []

    def walk(obj, depth):
        if depth > 4:
            return
        out.append(obj)
        if isinstance(obj, dict):
            children = obj.values()
        elif isinstance(obj, list):
            children = list(obj)
        elif isinstance(obj, Table):
            children = list(obj.rows)
        else:
            return
        for child in children:
            if isinstance(child, (dict, list, Table)):
                walk(child, depth + 1)

    walk(doc, 0)
    return out


def _random_nested_edit(rng, doc, actor):
    """One change mutating a random container anywhere in the tree."""

    def cb(d):
        targets = _containers(d)
        obj = rng.choice(targets)
        if isinstance(obj, Table):
            ids = obj.ids
            if ids and rng.random() < 0.3:
                obj.remove(rng.choice(ids))
            else:
                obj.add({"title": f"{actor}-{rng.randrange(99)}",
                         "nested": _random_value(rng, 2)})
        elif isinstance(obj, list):
            if len(obj) and rng.random() < 0.35:
                obj.delete_at(rng.randrange(len(obj)))
            else:
                obj.insert(rng.randint(0, len(obj)),
                           _random_value(rng, 1))
        else:  # map (root or nested)
            key = rng.choice("abcde")
            r = rng.random()
            if key in obj and isinstance(obj[key], am.Counter):
                # counters cannot be overwritten (reference semantics):
                # increment or delete only
                if r < 0.3:
                    del obj[key]
                else:
                    obj[key].increment(rng.randrange(1, 4))
            elif key in obj and r < 0.25:
                del obj[key]
            elif key in obj and isinstance(obj[key], Text) and r < 0.5:
                t = obj[key]
                t.insert_at(rng.randint(0, len(t)), rng.choice("mn"))
            else:
                obj[key] = _random_value(rng, 0)

    return am.change(doc, cb)


def test_nested_fuzz_never_graduates():
    """Random nested multi-actor histories (edits, merges, undo/redo,
    save/load) stay on the device tier end to end: zero graduations."""
    for seed in range(4):
        rng = random.Random(31_000 + seed)
        device_backend.GRADUATION_STATS.clear()
        n_actors = rng.randint(2, 3)
        base = am.change(am.init("base"),
                         lambda d: d.update({"seed": 1}))
        base_changes = am.get_all_changes(base)
        docs = [am.apply_changes(am.init(f"actor-{i}"), base_changes)
                for i in range(n_actors)]

        for _ in range(5):
            for i in range(n_actors):
                if rng.random() < 0.85:
                    docs[i] = _random_nested_edit(rng, docs[i],
                                                  f"actor-{i}")
                if rng.random() < 0.15 and am.can_undo(docs[i]):
                    docs[i] = am.undo(docs[i])
                    if rng.random() < 0.5 and am.can_redo(docs[i]):
                        docs[i] = am.redo(docs[i])
            i, j = rng.sample(range(n_actors), 2)
            docs[i] = am.merge(docs[i], docs[j])

        merged = docs[0]
        for d in docs[1:]:
            merged = am.merge(merged, d)
        merged = am.load(am.save(merged))          # replay path too
        am.to_json(merged)                          # full materialization
        assert isinstance(Frontend.get_backend_state(merged),
                          device_backend.DeviceBackendState), \
            f"seed {seed}: left the device tier"
        assert device_backend.GRADUATION_STATS == {}, \
            f"seed {seed}: graduated on well-formed input: " \
            f"{device_backend.GRADUATION_STATS}"


def test_malformed_delivery_is_the_only_graduation_trigger():
    """An op action outside the wire schema — the one remaining trigger —
    is surfaced in GRADUATION_STATS and then authoritatively REJECTED by
    the oracle (the reference throws on unknown op types too,
    backend/op_set.js applyOps); the prior document state stays usable."""
    import pytest

    device_backend.GRADUATION_STATS.clear()
    doc = am.change(am.init("aaaa"), lambda d: d.__setitem__("x", 1))
    malformed = {"actor": "zzzz", "seq": 1, "deps": {}, "ops": [
        {"action": "frobnicate", "obj": am.ROOT_ID, "key": "z"},
    ]}
    with pytest.raises(ValueError, match="Unknown operation type"):
        am.apply_changes(doc, [malformed])
    assert device_backend.GRADUATION_STATS == {"out_of_scope": 1}
    # the failed delivery left the original document fully usable
    assert am.to_json(doc) == {"x": 1}
    doc2 = am.change(doc, lambda d: d.__setitem__("y", 2))
    assert am.to_json(doc2) == {"x": 1, "y": 2}


def test_scope_gate_rejects_kind_overwrite_after_ins():
    """The one non-monotone predicate in the scope gate: an ins whose
    target's kind is OVERWRITTEN by a later make in the same delivery
    must be rejected on the final kind (single-pass regression,
    round-5 review counterexample), while make-after-use of a fresh
    text stays admitted."""
    from automerge_tpu.backend.device import _in_scope

    overwrite = [{"ops": [
        {"action": "ins", "obj": "o1", "key": "_head", "elem": 1},
        {"action": "makeMap", "obj": "o1"},
    ]}]
    assert _in_scope(overwrite, {"o1": "text"}) is False

    make_after_use = [{"ops": [
        {"action": "ins", "obj": "o2", "key": "_head", "elem": 1},
        {"action": "makeText", "obj": "o2"},
    ]}]
    assert _in_scope(make_after_use, {}) is True

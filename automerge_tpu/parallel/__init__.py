from .mesh import batched_merge_step, make_mesh, sharded_merge_step  # noqa: F401

"""Checkpoint & compaction tier: columnar snapshots + delta restore.

``api.save()`` serializes the full change log and ``load()`` replays every
change through the round protocol, so cold-starting a large document pays
its entire ingest history again — and a late-joining sync peer pays it
over the wire. The reference cannot compact its op log at all
(INTERNALS §3); this tier adds the capability the TPU rebuild makes
natural (PAM-style persistent snapshots + Jiffy's batch/snapshot split,
PAPERS.md): snapshot the engine and backend state *directly*.

Pieces (docs/INTERNALS.md §8):

- :mod:`.bundle` — the versioned manifest + per-array SHA-256 container.
  Corruption of any byte raises the typed :class:`CheckpointError` before
  restored state escapes.
- :mod:`.engine_codec` — ``DeviceTextDoc``/``DeviceMapDoc`` columnar
  tables, host range index, and causal host state; restore = one h2d
  staging pass, no replay (the bench-pinned ≥5x win,
  ``restore_snapshot_s`` vs ``restore_full_replay_s``).
- :mod:`.backend_codec` — whole lineages (device core or oracle state),
  history-complete so a restored doc syncs/saves like the original.
- :mod:`.writer` — the async capture path riding the PR 2 double-buffer
  seam: generation-checked grabs overlap ingestion, degrading to a
  synchronous grab on sustained conflict.
- delta saves (:func:`save_delta` / ``api.save(doc, checkpoint=...)``) —
  a checkpoint records the clock frontier it covers; later saves carry
  only the op-log tail, and restore = snapshot + tail replay.
- snapshot-bootstrapped sync — ``SyncHub``/``DocSet`` hand joining peers
  a checkpoint + tail instead of full history (sync/hub.py), with
  CheckpointError falling back to full log replay.
"""

from __future__ import annotations

import base64
import json

from .._common import less_or_equal
from ..resilience.errors import CheckpointError  # noqa: F401  (re-export)
from . import bundle as _bundle
from .backend_codec import (  # noqa: F401
    capture_state, restore_state, restore_state_or_replay,
)
from .writer import AsyncCheckpointer, CheckpointHandle  # noqa: F401

DELTA_FORMAT = "automerge-tpu-delta-v1"


class Checkpoint:
    """A checkpoint bundle plus its cheap metadata (id, frontier clock).

    Wraps the raw bundle bytes; the manifest is peeked lazily (header
    parse only — full integrity verification happens at restore)."""

    __slots__ = ("data", "_id", "_manifest")

    def __init__(self, data: bytes):
        self.data = bytes(data)
        self._id = None
        self._manifest = None

    @classmethod
    def wrap(cls, obj) -> "Checkpoint":
        if isinstance(obj, Checkpoint):
            return obj
        if isinstance(obj, (bytes, bytearray, memoryview)):
            return cls(obj)
        raise CheckpointError(
            f"expected a Checkpoint or bundle bytes, got "
            f"{type(obj).__name__}")

    @property
    def id(self) -> str:
        if self._id is None:
            self._id = _bundle.bundle_id(self.data)
        return self._id

    @property
    def manifest(self) -> dict:
        if self._manifest is None:
            self._manifest = _bundle.peek(self.data)
        return self._manifest

    @property
    def clock(self) -> dict:
        """The clock frontier this checkpoint covers."""
        return dict(self.manifest.get("clock", {}))

    def to_base64(self) -> str:
        return base64.b64encode(self.data).decode("ascii")

    @classmethod
    def from_base64(cls, text: str) -> "Checkpoint":
        try:
            return cls(base64.b64decode(text.encode("ascii"),
                                        validate=True))
        except (ValueError, UnicodeEncodeError) as exc:
            raise CheckpointError(
                f"checkpoint is not valid base64: {exc}") from None

    def __len__(self):
        return len(self.data)


# ---------------------------------------------------------------------------
# document-level capture/restore
# ---------------------------------------------------------------------------

def checkpoint_doc(doc) -> Checkpoint:
    """Capture a frontend document's backend lineage into a checkpoint."""
    from .. import frontend as Frontend
    state = Frontend.get_backend_state(doc)
    if state is None:
        raise CheckpointError(
            "this object has no backend state to checkpoint (a snapshot "
            "from the history?)")
    return Checkpoint(capture_state(state))


def restore_doc(checkpoint, options=None):
    """A frontend document restored from a checkpoint bundle (verified)."""
    state = restore_state(Checkpoint.wrap(checkpoint).data)
    return _doc_from_state(state, options)


def restore_doc_or_replay(checkpoint, fallback_changes, options=None):
    """Restore a document; a corrupt bundle falls back to full log replay
    of ``fallback_changes`` (raises CheckpointError when none given)."""
    ck = Checkpoint.wrap(checkpoint)
    state = restore_state_or_replay(ck.data, fallback_changes)
    return _doc_from_state(state, options)


def _doc_from_state(state, options=None):
    from .. import frontend as Frontend
    from ..api import init
    from ..backend import default as Backend
    patch = Backend.get_patch(state)
    patch["state"] = state
    return Frontend.apply_patch(init(options), patch)


# ---------------------------------------------------------------------------
# delta saves (compaction)
# ---------------------------------------------------------------------------

def save_delta(state, checkpoint) -> str:
    """A compacted save: only the op-log tail past the checkpoint's clock
    frontier (the covered prefix is dropped — the compaction contract;
    ``api.load`` needs the base checkpoint back to restore it)."""
    from ..backend import default as Backend
    ck = Checkpoint.wrap(checkpoint)
    frontier = ck.clock
    if not less_or_equal(frontier, dict(state.clock)):
        raise ValueError(
            "checkpoint is not an ancestor of this document (its frontier "
            "exceeds the document clock)")
    tail = Backend.get_missing_changes(state, frontier)
    tail = tail + [c for c in state.queue
                   if c.get("seq", 0) > frontier.get(c.get("actor"), 0)]
    return json.dumps({"format": DELTA_FORMAT, "checkpointId": ck.id,
                       "frontier": frontier, "changes": tail})


def load_delta(payload: dict, checkpoint, options=None):
    """Restore a delta save: verified snapshot restore + tail replay."""
    if checkpoint is None:
        raise ValueError(
            "this save is delta-compacted; pass its base checkpoint "
            "(load(data, checkpoint=...))")
    ck = Checkpoint.wrap(checkpoint)
    want = payload.get("checkpointId")
    if want is not None and want != ck.id:
        raise CheckpointError(
            f"wrong base checkpoint: save references {want!r}, got "
            f"{ck.id!r}")
    doc = restore_doc(ck, options)
    tail = payload.get("changes") or []
    if tail:
        from ..api import apply_changes
        doc = apply_changes(doc, tail)
    return doc


# ---------------------------------------------------------------------------
# engine-doc capture/restore (the bench-level building block)
# ---------------------------------------------------------------------------

def capture_engine(doc) -> bytes:
    """A standalone bundle of one engine doc (DeviceTextDoc/DeviceMapDoc)."""
    return AsyncCheckpointer.capture(doc)


def restore_engine(data: bytes):
    """Rebuild an engine doc from a :func:`capture_engine` bundle."""
    from .engine_codec import restore_engine_doc
    manifest, arrays = _bundle.decode(data)
    if manifest.get("engine") != "engine-doc":
        raise CheckpointError(
            f"not an engine-doc checkpoint: {manifest.get('engine')!r}")
    frag = manifest.get("doc")
    if not isinstance(frag, dict):
        raise CheckpointError("engine-doc checkpoint is missing its doc "
                              "fragment")
    return restore_engine_doc(frag, arrays)


__all__ = [
    "AsyncCheckpointer", "Checkpoint", "CheckpointError",
    "CheckpointHandle", "DELTA_FORMAT", "capture_engine", "capture_state",
    "checkpoint_doc", "load_delta", "restore_doc", "restore_doc_or_replay",
    "restore_engine", "restore_state", "restore_state_or_replay",
    "save_delta",
]

"""Learned-index host planning: exactness, parity, demotion (ISSUE 19).

The bounded-error position models (engine/learned_index.py) are
*advisory*: a model prediction is verified in its ε-window and a failed
verify is a counted fallback to the exact probe — NEVER a wrong answer.
These tests pin that contract three ways:

- model-level exactness over randomized tables and query distributions
  (both searchsorted sides, packed string keys, the full-key equality
  gate that prevents prefix aliasing);
- byte-identity of committed engine state across the
  ``AMTPU_LEARNED_INDEX`` × ``AMTPU_CROSS_DOC_PLAN`` ×
  ``AMTPU_BATCH_INDEX`` flag matrix on shuffled/dup/premature streams
  (the PR-5/7 parity discipline: the exact paths stay verbatim behind
  the flag);
- adversarial drift: a deliberately under-bounded model (the stale-model
  shape that cannot arise from `fit_model`'s closed-form ε, simulated
  directly) must stay exact through every miss, cross the miss-rate
  window into demotion, and re-arm on refit — the
  refit-on-intern-gen-bump pin rides the same token.
"""

import random

import numpy as np
import pytest

from automerge_tpu.engine import learned_index as li
from test_columnar_plan import (_run_population, apply_with_flag,
                                rand_text_changes)


@pytest.fixture(autouse=True)
def _fresh_stats():
    li.reset_stats()
    yield
    li.reset_stats()


# ---------------------------------------------------------------------------
# model-level exactness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(8))
def test_model_searchsorted_exact_random_tables(seed):
    """Model-predicted positions equal np.searchsorted on both sides for
    random non-uniform int64 tables and mixed member/miss queries."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(li._min_keys(), 4000))
    # lognormal gaps: deliberately non-linear key space
    gaps = np.maximum(1, rng.lognormal(2.0, 2.0, n)).astype(np.int64)
    keys = np.cumsum(gaps)
    m = li.fit_model(keys, "range_index")
    if m is None:      # ε over the refusal cap for this draw: exact path
        return
    q = np.concatenate([
        rng.choice(keys, 50),                        # members
        keys[rng.integers(0, n, 50)] + rng.integers(-3, 4, 50),  # near
        rng.integers(0, int(keys[-1]) + 10, 50),     # uniform
    ])
    for side in ("left", "right"):
        got = m.searchsorted(q, side=side)
        np.testing.assert_array_equal(got, np.searchsorted(keys, q, side))


def test_eps_is_exact_bound_and_refusal():
    """ε is the measured max model error at fit time; a table whose ε
    would exceed the cap refuses to build (the window would out-read a
    binary search)."""
    keys = np.arange(0, 10_000, 7, dtype=np.int64)
    m = li.fit_model(keys, "range_index")
    assert m is not None and m.eps == 0   # affine table: exact model
    # two dense clusters with one huge gap and only 2 anchors would err;
    # with the default anchor budget ε stays small — force refusal via a
    # pathological table wider than any plausible ε cap
    rng = np.random.default_rng(0)
    bad = np.cumsum(np.maximum(
        1, rng.pareto(0.3, 5000) * 1e6).astype(np.int64))
    m2 = li.fit_model(bad, "range_index")
    if m2 is not None:
        assert m2.eps <= li._max_eps()


def test_pack_str_keys_order_and_refusals():
    vals = ["a", "ab", "b", "zz9", "zzz"]
    packed = li.pack_str_keys(vals)
    assert packed is not None
    assert (packed[1:] > packed[:-1]).all()   # order-preserving
    assert li.pack_str_keys(["café"]) is None   # non-ASCII: refuse


def test_actor_positions_prefix_collision_never_aliases():
    """Two actors sharing an 8-byte prefix make the packed table
    non-strictly-increasing — the site must refuse (exact path), never
    return an aliased rank."""
    table = sorted(["actor-000017-a", "actor-000017-b", "b"])
    got = li.actor_positions(table, np.asarray(["actor-000017-b"], object),
                             "actor_rank")
    assert got is None
    assert li.SITES["actor_rank"].exact_fallbacks >= 1


def test_actor_positions_full_key_gate():
    """Found is full-key equality, not prefix equality: a query whose
    8-byte prefix matches a table entry but whose tail differs reports
    not-found."""
    table = sorted(f"w{i:07d}" for i in range(64))       # exactly 8 bytes
    q = np.asarray(["w0000003", "w0000003x", "w9999999"], object)
    got = li.actor_positions(table, q, "actor_rank")
    assert got is not None
    pos, found = got
    assert found.tolist() == [True, False, False]
    assert pos[0] == 3


# ---------------------------------------------------------------------------
# flag-matrix byte-identity parity (shuffled / dup / premature streams)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(2))
@pytest.mark.parametrize("cross,batch", [("1", "1"), ("1", "0"),
                                         ("0", "1"), ("0", "0")])
def test_flag_matrix_population_parity(seed, cross, batch, monkeypatch):
    """Committed population state is byte-identical with the learned
    paths on vs off, under every AMTPU_CROSS_DOC_PLAN ×
    AMTPU_BATCH_INDEX combination, over randomized out-of-order/
    duplicate/premature chunked deliveries."""
    monkeypatch.setenv("AMTPU_LEARNED_INDEX", "0")
    ref = _run_population(seed, cross, "1", monkeypatch,
                          batch_index=batch)
    monkeypatch.setenv("AMTPU_LEARNED_INDEX", "1")
    got = _run_population(seed, cross, "1", monkeypatch,
                          batch_index=batch)
    assert got == ref


@pytest.mark.parametrize("seed", range(3))
def test_wide_actor_batch_parity(seed, monkeypatch):
    """A single wide batch minting many actors (the learned
    `_intern_batch_actors` membership scan engages above its size
    threshold) commits byte-identically with the learned path on/off."""
    rng = random.Random(seed)
    changes = rand_text_changes(rng, n_changes=40, n_actors=16,
                                premature=False)
    monkeypatch.setenv("AMTPU_LEARNED_INDEX", "0")
    ref = apply_with_flag(list(changes), "1", monkeypatch)
    monkeypatch.setenv("AMTPU_LEARNED_INDEX", "1")
    got = apply_with_flag(list(changes), "1", monkeypatch)
    assert got == ref


def test_unknown_parent_same_error_both_paths(monkeypatch):
    """The learned resolver raises the exact path's unknown-parent
    signal verbatim (message parity is part of the comparator
    contract)."""
    from automerge_tpu.engine.text_doc import DeviceTextDoc
    bad = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "ghost:99", "elem": 1}]}]
    msgs = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("AMTPU_LEARNED_INDEX", flag)
        doc = DeviceTextDoc("t")
        doc.apply_changes([{"actor": "b", "seq": 1, "deps": {}, "ops": [
            {"action": "ins", "obj": "t", "key": "_head", "elem": 1}]}])
        with pytest.raises(ValueError) as ei:
            doc.apply_changes([dict(c) for c in bad])
        msgs[flag] = str(ei.value)
    assert msgs["0"] == msgs["1"]


# ---------------------------------------------------------------------------
# adversarial drift: misses stay exact, demote, re-arm on refit
# ---------------------------------------------------------------------------


def test_drifted_model_misses_stay_exact_then_demote():
    """A model whose ε under-states the true error (the stale/drifted
    shape — unreachable through fit_model's closed-form ε, built
    directly here) must fall back per missing key with the EXACT answer,
    and enough window misses must demote the site to the exact path."""
    st = li.SITES["range_index"]
    rng = np.random.default_rng(3)
    keys = np.cumsum(np.maximum(
        1, rng.lognormal(3.0, 2.5, 2000)).astype(np.int64))
    good = li.fit_model(keys, "range_index")
    assert good is not None
    # same anchors, lying ε=0: every prediction off by >0 now misses
    drifted = li.PositionModel(good.padded, good.anchor_keys,
                               good.anchor_pos, 0, "range_index")
    q = rng.integers(0, int(keys[-1]), 4000)
    rounds = 0
    while not st.demoted and rounds < 40:
        got = drifted.searchsorted(q, side="left")
        np.testing.assert_array_equal(got, np.searchsorted(keys, q))
        rounds += 1
    assert st.demoted, "miss-rate window never demoted the site"
    assert st.misses > 0 and st.wrong == 0
    assert not li.site_enabled("range_index")   # consumers go exact
    # a refit (the interning-generation-bump trigger) re-arms the site
    li.fit_model(keys, "range_index")
    assert not st.demoted
    assert li.site_enabled("range_index")


def test_actor_churn_forces_exact_fallbacks_never_wrong(monkeypatch):
    """Non-append actor churn (fresh interleaving actors every round —
    each bump refits) keeps the learned population byte-identical to the
    exact comparator; every probe either hits or is a counted fallback,
    never a wrong answer."""
    states = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("AMTPU_LEARNED_INDEX", flag)
        monkeypatch.setenv("AMTPU_LEARNED_AUDIT", "1")
        li.reset_stats()
        rng = random.Random(11)
        # interleaving actor names: aa.., am.., ab.. sort between each
        # other so every round's interning is a general (non-append)
        # merge — the churn shape that would punish a stale model
        changes = []
        known = ["_head"]
        ctr = 1
        for rnd in range(12):
            actor = f"a{chr(97 + (rnd * 7) % 26)}{rnd:02d}"
            ops = []
            for _ in range(6):
                parent = rng.choice(known)
                ops.append({"action": "ins", "obj": "t", "key": parent,
                            "elem": ctr})
                ops.append({"action": "set", "obj": "t",
                            "key": f"{actor}:{ctr}", "value": "x"})
                known.append(f"{actor}:{ctr}")
                ctr += 1
            changes.append({"actor": actor, "seq": 1, "deps": {},
                            "ops": ops})
        states[flag] = apply_with_flag(changes, "1", monkeypatch,
                                       seed_doc=False)
        if flag == "1":
            snap = li.stats_snapshot()
            assert all(s["wrong"] == 0 for s in snap.values()), snap
    assert states["1"] == states["0"]


def test_refit_on_intern_gen_bump():
    """The per-(doc, intern-gen) actor model retrains exactly when the
    PR-5 invalidation token bumps — same token, same trigger."""
    from automerge_tpu.engine.text_doc import DeviceTextDoc
    doc = DeviceTextDoc("t")
    doc.apply_changes([{"actor": f"a{i:02d}", "seq": 1,
                        "deps": {}, "ops": []} for i in range(20)])
    st = li.SITES["actor_rank"]
    m1 = li.doc_actor_model(doc)
    r1 = st.refits
    assert li.doc_actor_model(doc) is m1      # cached: no refit
    assert st.refits == r1
    gen0 = doc._intern_gen
    doc.apply_changes([{"actor": "zz99", "seq": 1, "deps": {},
                        "ops": []}])          # new actor: gen bump
    assert doc._intern_gen != gen0
    m2 = li.doc_actor_model(doc)
    assert m2 is not m1
    assert st.refits > r1


def test_range_index_model_invalidates_across_merges():
    """BatchRangeIndex keeps its cached tier model only while the fitted
    tier's runs are identity-preserved by a merge; a changed tier refits
    rather than serving stale predictions."""
    from automerge_tpu.engine import host_index as H
    idx = H.BatchRangeIndex()
    n = max(li._min_keys(), H._MIN_MODEL_RANGES) + 8
    starts = np.arange(0, n * 100, 100, dtype=np.int64)
    idx = idx.merge(starts, np.full(n, 3, np.int64),
                    np.arange(1, 3 * n, 3, dtype=np.int64))
    keys = starts + 1
    s1, f1 = idx.lookup_learned(keys)
    se, fe = idx.lookup(keys)
    np.testing.assert_array_equal(s1, se)
    np.testing.assert_array_equal(f1, fe)
    cached = idx._model
    assert cached is not None
    # non-adjacent second merge: a new tier appears, tier-0 runs keep
    # identity, so the cached model survives the merge
    idx2 = idx.merge(np.asarray([10 ** 9], np.int64),
                     np.asarray([5], np.int64),
                     np.asarray([5000], np.int64))
    assert idx2._model is cached
    q2 = np.concatenate([keys[:4], np.asarray([10 ** 9 + 2], np.int64)])
    sl, fl = idx2.lookup_learned(q2)
    sx, fx = idx2.lookup(q2)
    np.testing.assert_array_equal(sl, sx)
    np.testing.assert_array_equal(fl, fx)


# ---------------------------------------------------------------------------
# residency_clock site
# ---------------------------------------------------------------------------


def test_store_member_mask_matches_exact_membership():
    from automerge_tpu.residency.store import BundleStore
    s = BundleStore()
    for i in range(48):
        s.put(f"doc{i:04d}", b"b" * 4)
    q = [f"doc{i:04d}" for i in range(0, 96, 5)]
    mask = s.member_mask(q)
    assert mask is not None
    assert mask.tolist() == [d in s for d in q]
    s.pop("doc0005")
    mask2 = s.member_mask(q)          # gen bump: table rebuilt
    assert mask2.tolist() == [d in s for d in q]


def test_store_member_mask_respects_flag(monkeypatch):
    from automerge_tpu.residency.store import BundleStore
    monkeypatch.setenv("AMTPU_LEARNED_INDEX", "0")
    s = BundleStore()
    s.put("d1", b"x")
    assert s.member_mask(["d1"]) is None   # exact comparator path

"""Checkpoint bundle container: versioned manifest + hashed array blobs.

An npz-style single-blob format, hand-rolled so corruption handling is
exact and deterministic:

    AMTPUCKPT1\\n | <u64 manifest length> | <sha256 of manifest bytes>
                 | <manifest JSON> | <array bytes>

The manifest is canonical JSON (sorted keys, no whitespace) carrying a
``format``/``version`` pair plus an ``arrays`` table — one entry per array
with name, dtype, shape, byte offset/length into the blob region, and a
SHA-256 content hash over ``dtype || shape || raw bytes``. The manifest
itself is covered by the header hash (clock, conflicts, value pools and
object metadata live there — a bit flip in those must fail like one in an
array). Large JSON payloads (the change history) ride as uint8 arrays so
they are hash-covered like everything else. Encoding is byte-deterministic
for a given (manifest, arrays) input — the async-capture identity tests
depend on that — so nothing time- or environment-dependent may enter here.

``decode()`` verifies structure, the manifest hash, and every array
content hash and raises the typed
:class:`~..resilience.errors.CheckpointError` on any truncation, bit
flip, or version mismatch, BEFORE any state is handed out.
"""

from __future__ import annotations

import hashlib
import json
import struct

import numpy as np

from ..resilience.errors import CheckpointError

MAGIC = b"AMTPUCKPT1\n"
FORMAT = "automerge-tpu-checkpoint"
VERSION = 1


def _array_hash(arr: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(arr.dtype.str.encode("ascii"))
    h.update(repr(tuple(arr.shape)).encode("ascii"))
    h.update(arr.tobytes())
    return h.hexdigest()


def bundle_id(data: bytes) -> str:
    """Stable identity of a bundle: SHA-256 over the full encoded bytes."""
    return hashlib.sha256(data).hexdigest()


def json_array(obj) -> np.ndarray:
    """A JSON-serializable object as a hash-coverable uint8 array.

    Keys keep their insertion order (NOT sorted): change dicts must
    round-trip byte-identically through ``api.save`` after a restore, and
    encoding stays deterministic for a given in-memory object either way."""
    raw = json.dumps(obj, separators=(",", ":"))
    return np.frombuffer(raw.encode("utf-8"), np.uint8)


def json_unarray(arr: np.ndarray):
    return json.loads(arr.tobytes().decode("utf-8"))


def encode(manifest: dict, arrays: dict) -> bytes:
    """Serialize (manifest, {name: np.ndarray}) to one bundle blob."""
    table = []
    blobs = []
    offset = 0
    for name in sorted(arrays):
        arr = np.ascontiguousarray(arrays[name])
        raw = arr.tobytes()
        table.append({"name": name, "dtype": arr.dtype.str,
                      "shape": list(arr.shape), "offset": offset,
                      "nbytes": len(raw), "sha256": _array_hash(arr)})
        blobs.append(raw)
        offset += len(raw)
    man = dict(manifest)
    man["format"] = FORMAT
    man["version"] = VERSION
    man["arrays"] = table
    mj = json.dumps(man, sort_keys=True, separators=(",", ":")).encode("utf-8")
    return (MAGIC + struct.pack("<Q", len(mj))
            + hashlib.sha256(mj).digest() + mj + b"".join(blobs))


def _parse_header(data):
    """Shared header parse + manifest integrity check for peek()/decode():
    -> (manifest dict, offset of the array-blob region)."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise CheckpointError(
            f"checkpoint bundle must be bytes, got {type(data).__name__}")
    data = bytes(data)
    hdr = len(MAGIC) + 8 + 32   # magic | u64 manifest len | manifest sha256
    if len(data) < hdr or not data.startswith(MAGIC):
        raise CheckpointError("checkpoint bundle has a bad or truncated "
                              "header (not an automerge-tpu checkpoint)")
    (mlen,) = struct.unpack_from("<Q", data, len(MAGIC))
    digest = data[len(MAGIC) + 8: hdr]
    if hdr + mlen > len(data):
        raise CheckpointError("checkpoint bundle truncated inside manifest")
    mj = data[hdr: hdr + mlen]
    if hashlib.sha256(mj).digest() != digest:
        raise CheckpointError(
            "checkpoint manifest failed its content hash (corrupt or "
            "tampered bundle)")
    try:
        manifest = json.loads(mj.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise CheckpointError(
            f"checkpoint manifest is not valid JSON: {exc}") from None
    if not isinstance(manifest, dict) or manifest.get("format") != FORMAT:
        raise CheckpointError(
            f"unsupported checkpoint format: "
            f"{manifest.get('format') if isinstance(manifest, dict) else manifest!r}")
    if manifest.get("version") != VERSION:
        raise CheckpointError(
            f"unsupported checkpoint version: {manifest.get('version')!r} "
            f"(this build reads version {VERSION})")
    return manifest, hdr + mlen


def peek(data: bytes) -> dict:
    """Parse a bundle's manifest (hash-verified) WITHOUT verifying array
    hashes — for cheap metadata reads (frontier clock, engine kind).
    Restore paths must go through :func:`decode`, which verifies the
    arrays too."""
    return _parse_header(data)[0]


def decode(data: bytes):
    """Parse + integrity-check a bundle -> (manifest, {name: np.ndarray}).

    Raises :class:`CheckpointError` on any structural or hash failure."""
    manifest, base = _parse_header(data)
    data = bytes(data)
    table = manifest.get("arrays")
    if not isinstance(table, list):
        raise CheckpointError("checkpoint manifest is missing its arrays "
                              "table")
    arrays = {}
    for ent in table:
        try:
            name = ent["name"]
            dtype = np.dtype(ent["dtype"])
            shape = tuple(ent["shape"])
            off, nbytes, digest = ent["offset"], ent["nbytes"], ent["sha256"]
        except (KeyError, TypeError, ValueError) as exc:
            raise CheckpointError(
                f"malformed checkpoint array entry: {exc}") from None
        lo = base + off
        if lo < base or lo + nbytes > len(data):
            raise CheckpointError(
                f"checkpoint bundle truncated inside array {name!r}")
        arr = np.frombuffer(data[lo: lo + nbytes], dtype)
        try:
            arr = arr.reshape(shape)
        except ValueError:
            raise CheckpointError(
                f"checkpoint array {name!r} shape/byte-length mismatch"
            ) from None
        if _array_hash(arr) != digest:
            raise CheckpointError(
                f"checkpoint array {name!r} failed its content hash "
                "(corrupt or tampered bundle)")
        arrays[name] = arr
    return manifest, arrays

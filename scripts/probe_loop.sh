#!/bin/bash
# Probe the TPU tunnel repeatedly for up to ~9.5 min; on success, launch the
# chip measurement session DETACHED (it outlives this probe process) and
# exit. Writes status lines to /tmp/tpu_probe_status.txt.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
# the chip admits ONE client and the probe IS a client: hold the session
# lock for the whole loop (a session in flight -> don't probe; our lock
# also keeps a session from starting mid-probe)
exec 9> /tmp/chip_session.lock
if ! flock -n 9; then
  echo "chip session in flight; not probing ($(date +%H:%M:%S))" >> /tmp/tpu_probe_status.txt
  exit 0
fi
for i in $(seq 1 6); do
  echo "probe $i at $(date +%H:%M:%S)" >> /tmp/tpu_probe_status.txt
  # shared strict probe (real computation, non-cpu platform) — see
  # scripts/probe_device.py for why the rule lives in exactly one file
  if timeout 80 python "$REPO/scripts/probe_device.py" >> /tmp/tpu_probe_status.txt 2>&1; then
    echo "TUNNEL_UP at $(date +%H:%M:%S) — launching chip session" >> /tmp/tpu_probe_status.txt
    exec 9>&-   # child takes its own lock; ours must be closed
    setsid nohup bash "$REPO/scripts/chip_session.sh" </dev/null \
      > /tmp/chip_session_nohup.log 2>&1 &
    exit 0
  fi
  sleep 10
done
echo "TUNNEL_DOWN after 6 probes at $(date +%H:%M:%S)" >> /tmp/tpu_probe_status.txt
exit 1

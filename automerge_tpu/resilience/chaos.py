"""Deterministic, seed-driven fault injection for sync transports.

A ``ChaosLink`` is one *directed* edge between a sender (anything with a
``send_msg``-shaped callback) and a receiver callback. Every fault decision
is drawn from one seeded generator in call order, so a session driven by a
fixed schedule of ``send``/``pump`` calls replays bit-identically from its
seed — the property the chaos soak harness (scripts/soak.py --chaos) relies
on to print reproducible failure seeds.

Fault model (per message, in this order):

- **partition**: while partitioned, every send is dropped outright (the
  TCP-connection-reset model: in-flight and new frames die; recovery is the
  layer above's job — `ResilientChannel` retransmit or peer reconnect).
  ``heal()`` restores the link.
- **drop**: lost with probability ``drop``.
- **duplicate**: enqueued twice with probability ``dup`` (each copy is an
  independent decode, so receiver-side aliasing can't mask dedup bugs).
- **delay**: each enqueued copy is due ``1..max_delay`` pump rounds late
  with probability ``delay``.
- **reorder**: with probability ``reorder`` the copy is inserted at a
  random position in the queue instead of the tail.

Every message is round-tripped through JSON (``codec=True``), which both
isolates the receiver from sender-side mutation and enforces the wire-format
invariant that sync messages are plain JSON — a tuple or numpy scalar
leaking into a message surfaces here, not in production. Binary change
frames (engine/wire_format.py) are the one non-JSON payload the wire
grammar defines: the codec carries them as base64 of their exact encoded
bytes and rebuilds a FRESH ``WireFrame`` per delivered copy, so every
receiver decodes its own frame from raw bytes — exactly the real-socket
semantics, and a duplicated copy cannot share a decode cache with the
original.
"""

from __future__ import annotations

import base64
import json

import numpy as np

from .. import obs

_WIRE_KEY = "__amtpu_wire_b64__"


def _codec_default(obj):
    from ..engine.wire_format import WireFrame
    if isinstance(obj, WireFrame):
        return {_WIRE_KEY: base64.b64encode(obj.data).decode("ascii")}
    raise TypeError(f"Object of type {type(obj).__name__} is not JSON "
                    "serializable")


def _codec_hook(d):
    if _WIRE_KEY in d and len(d) == 1:
        from ..engine.wire_format import WireFrame
        return WireFrame(base64.b64decode(d[_WIRE_KEY]))
    return d


class ChaosLink:
    def __init__(self, deliver, *, seed: int = 0, rng=None,
                 drop: float = 0.0, dup: float = 0.0, reorder: float = 0.0,
                 delay: float = 0.0, max_delay: int = 3,
                 bandwidth: int = 0, codec: bool = True):
        self._deliver = deliver
        self._rng = rng if rng is not None else np.random.default_rng(seed)
        self.drop = drop
        self.dup = dup
        self.reorder = reorder
        self.delay = delay
        self.max_delay = max_delay
        #: per-direction bandwidth cap: at most this many payload wire
        #: bytes delivered per pump round (0 = unlimited). Frames past
        #: the budget HOLD to later rounds (never drop — a WAN's queue,
        #: not its loss), counted in ``throttled``. Asymmetric
        #: cross-region paths set different caps per direction (the WAN
        #: profiles below).
        self.bandwidth = bandwidth
        self.codec = codec
        self.partitioned = False
        self._queue: list = []        # [due_round, payload]
        self._round = 0
        self.stats = {"sent": 0, "delivered": 0, "dropped": 0,
                      "partition_dropped": 0, "duplicated": 0,
                      "reordered": 0, "delayed": 0, "throttled": 0}

    # -- fault schedule -------------------------------------------------

    def partition(self):
        """Sever the link: in-flight frames die, new sends are dropped."""
        self.partitioned = True
        self.stats["partition_dropped"] += len(self._queue)
        if obs.ENABLED:
            obs.event("chaos", "partition",
                      args={"in_flight_dropped": len(self._queue)})
        self._queue.clear()

    def heal(self):
        self.partitioned = False

    # -- transport face -------------------------------------------------

    def send(self, msg):
        self.stats["sent"] += 1
        wire = json.dumps(msg, default=_codec_default) \
            if self.codec else msg
        if self.partitioned:
            self.stats["partition_dropped"] += 1
            if obs.ENABLED:
                obs.event("chaos", "partition_drop")
            return
        if self.drop and self._rng.random() < self.drop:
            self.stats["dropped"] += 1
            if obs.ENABLED:
                obs.event("chaos", "drop")
            return
        copies = 1
        if self.dup and self._rng.random() < self.dup:
            copies = 2
            self.stats["duplicated"] += 1
            if obs.ENABLED:
                obs.event("chaos", "dup")
        for _ in range(copies):
            payload = (json.loads(wire, object_hook=_codec_hook)
                       if self.codec else msg)
            due = self._round
            if self.delay and self._rng.random() < self.delay:
                due += int(self._rng.integers(1, self.max_delay + 1))
                self.stats["delayed"] += 1
                if obs.ENABLED:
                    obs.event("chaos", "delay",
                              args={"rounds": due - self._round})
            entry = [due, payload]
            if self.reorder and self._queue \
                    and self._rng.random() < self.reorder:
                at = int(self._rng.integers(0, len(self._queue)))
                self._queue.insert(at, entry)
                self.stats["reordered"] += 1
                if obs.ENABLED:
                    obs.event("chaos", "reorder")
            else:
                self._queue.append(entry)

    def pump(self) -> int:
        """Advance one round and deliver every due frame — up to the
        bandwidth cap when one is set; over-budget frames hold (queue
        order preserved) and count as ``throttled``. Returns the number
        delivered."""
        self._round += 1
        budget = self.bandwidth or None
        due, held = [], []
        for entry in self._queue:
            if entry[0] >= self._round:
                held.append(entry)
                continue
            if budget is not None:
                if budget <= 0:
                    self.stats["throttled"] += 1
                    held.append(entry)
                    continue
                from .channel import payload_wire_bytes
                budget -= payload_wire_bytes(entry[1])
            due.append(entry)
        self._queue = held
        for _, payload in due:
            self._deliver(payload)
        self.stats["delivered"] += len(due)
        return len(due)

    def drain(self, max_rounds: int = 64) -> int:
        """Pump until the queue is empty (bounded); returns total
        delivered. Faults still apply to anything sent re-entrantly."""
        total = 0
        for _ in range(max_rounds):
            if not self._queue:
                break
            total += self.pump()
        return total

    @property
    def idle(self) -> bool:
        return not self._queue


#: Named seeded WAN profiles (ISSUE 16): per-direction fault kwargs for
#: a cross-region path, deliberately ASYMMETRIC — real WANs are (a fat
#: egress pipe toward a thin return path, jitter that differs by
#: direction). ``fwd`` is the a->b direction of :func:`wan_pair`,
#: ``rev`` the b->a direction. Delay units are pump rounds (the
#: federation pumps once per service tick, so `max_delay=20` models a
#: high-RTT path ~20 ticks deep); ``bandwidth`` is payload wire bytes
#: per round. Shared by scripts/soak.py --federation and the tests —
#: ONE definition, so the soak and the acceptance tests can never drift
#: onto different fault models.
WAN_PROFILES = {
    # steady high-RTT inter-region path: mild loss, deep delay, fat
    # forward / thin return bandwidth
    "wan": {
        "fwd": dict(drop=0.02, dup=0.01, reorder=0.10, delay=0.6,
                    max_delay=12, bandwidth=96 * 1024),
        "rev": dict(drop=0.03, dup=0.01, reorder=0.15, delay=0.7,
                    max_delay=20, bandwidth=32 * 1024),
    },
    # a flapping path trending toward partition: heavy loss + jitter
    # (the explicit partition()/heal() windows ride on top)
    "wan_partitioned": {
        "fwd": dict(drop=0.15, dup=0.02, reorder=0.20, delay=0.8,
                    max_delay=24, bandwidth=48 * 1024),
        "rev": dict(drop=0.20, dup=0.02, reorder=0.25, delay=0.8,
                    max_delay=32, bandwidth=16 * 1024),
    },
    # the federation default: moderate chaos both ways, asymmetric
    # delay/bandwidth — survivable by retransmission without tripping
    # the retry cap against a live peer
    "cross_region": {
        "fwd": dict(drop=0.05, dup=0.02, reorder=0.15, delay=0.5,
                    max_delay=8, bandwidth=64 * 1024),
        "rev": dict(drop=0.08, dup=0.02, reorder=0.20, delay=0.6,
                    max_delay=14, bandwidth=24 * 1024),
    },
}


def wan_profile(name: str, direction: str = "fwd") -> dict:
    """One direction's ChaosLink kwargs from a named WAN profile (typed
    KeyError on an unknown name — a misspelled profile must not silently
    run lossless)."""
    prof = WAN_PROFILES.get(name)
    if prof is None:
        raise KeyError(f"unknown WAN profile {name!r}; known: "
                       f"{sorted(WAN_PROFILES)}")
    return dict(prof[direction])


def wan_pair(deliver_fwd, deliver_rev, *, profile: str = "cross_region",
             seed: int = 0):
    """A seeded directed ChaosLink pair for one inter-region path:
    ``(fwd, rev)`` where `fwd` carries a->b under the profile's ``fwd``
    kwargs and `rev` carries b->a under ``rev``. The two links draw from
    independent seeded generators (seed, seed+1), so one direction's
    fault schedule replays bit-identically regardless of the other's
    traffic order."""
    fwd = ChaosLink(deliver_fwd, seed=seed, **wan_profile(profile, "fwd"))
    rev = ChaosLink(deliver_rev, seed=seed + 1,
                    **wan_profile(profile, "rev"))
    return fwd, rev

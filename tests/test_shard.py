"""The sharded serving tier (automerge_tpu/shard, INTERNALS §15).

The tier's contract is shard-count INVARIANCE: the same seeded chaotic
session — full cross-doc shuffle (causally-premature arrivals park in
the router quarantine), duplicated deliveries, telemetry-triggered
hot-doc migration mid-stream — must converge to byte-identical state
(checkpoint-bundle bytes AND rendered texts) on 1, 2, and 8 shards.
Plus: deterministic placement, the migration protocol's quarantine
handshake (a doc moves while premature changes for it sit parked, and
while fresh deliveries arrive mid-move), the per-lane stacked dispatch
budget, the seeded-positions emission bound (ROADMAP 1a), the DocSet
stacked unification (ROADMAP 1b), the zero-collective HLO audit, and
the SyncService room→lane wiring."""

import os

import numpy as np
import pytest

from automerge_tpu.engine import stacked
from automerge_tpu.shard import PlacementTable, ShardLane, ShardedDocSet
from automerge_tpu.shard.placement import hash_shard


@pytest.fixture(autouse=True)
def _small_gate(monkeypatch):
    """Engage the stacked path at test scale (the production gate skips
    tiny interactive rounds)."""
    monkeypatch.setenv("AMTPU_STACKED_MIN_OPS", "1")


def text_change(actor, seq, text, start_ctr=1, after=None, deps=None,
                obj="t"):
    ops = []
    key = after if after is not None else "_head"
    for i, c in enumerate(text):
        ctr = start_ctr + i
        ops.append({"action": "ins", "obj": obj, "key": key, "elem": ctr})
        ops.append({"action": "set", "obj": obj, "key": f"{actor}:{ctr}",
                    "value": c})
        key = f"{actor}:{ctr}"
    return {"actor": actor, "seq": seq, "deps": deps or {}, "ops": ops}


def map_change(actor, seq, obj, items, deps=None):
    return {"actor": actor, "seq": seq, "deps": deps or {},
            "ops": [{"action": "set", "obj": obj, "key": k, "value": v}
                    for k, v in items]}


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------


class TestPlacement:
    def test_hash_is_process_stable_and_in_range(self):
        # sha1-derived, NOT the salted builtin hash: the same doc id
        # must land on the same shard on every host/run/process
        assert hash_shard("doc-00042", 8) == hash_shard("doc-00042", 8)
        for n in (1, 2, 8, 13):
            assert 0 <= hash_shard("any-doc", n) < n
        # pin one value: a silent hash change would shuffle EVERY
        # existing population's ownership on upgrade
        assert hash_shard("doc-00042", 8) == \
            int.from_bytes(__import__("hashlib").sha1(
                b"doc-00042").digest()[:8], "big") % 8

    def test_hash_spreads_a_population(self):
        table = PlacementTable(8)
        spread = table.spread(f"doc-{i:04d}" for i in range(800))
        assert sum(spread) == 800
        assert all(c > 0 for c in spread)          # nothing starves
        assert max(spread) < 3 * min(spread)       # roughly balanced

    def test_overrides_move_epoch_and_drop(self):
        table = PlacementTable(4)
        doc = "mover"
        home = table.shard_of(doc)
        away = (home + 1) % 4
        assert table.epoch == 0 and table.table() == {}
        table.move(doc, away)
        assert table.shard_of(doc) == away
        assert table.table() == {doc: away} and table.epoch == 1
        # moving back to the hash home drops the override: the table
        # never accretes entries that restate the hash
        table.move(doc, home)
        assert table.table() == {} and table.epoch == 2
        assert table.shard_of(doc) == home
        with pytest.raises(ValueError):
            table.move(doc, 4)
        with pytest.raises(ValueError):
            PlacementTable(0)


# ---------------------------------------------------------------------------
# the lane: stacked budget + seeded-positions emission bound
# ---------------------------------------------------------------------------


class TestLane:
    def test_map_lane_ingest_is_one_stacked_apply(self):
        lane = ShardLane(0, doc_kind="map")
        deliveries = {f"m{i}": [map_change("a", 1, f"m{i}",
                                           [(f"k{j}", i * 10 + j)
                                            for j in range(4)])]
                      for i in range(6)}
        n = lane.ingest(deliveries)
        assert n == 24
        # ONE stacked apply for the whole round; the per-round dispatch
        # budget (object-count independent) was asserted inside ingest
        assert lane.stats["stacked_applies"] == 1
        assert lane.stats["per_object_applies"] == 0
        assert lane.docs["m3"].to_dict()["k2"] == 32

    def test_text_lane_seeds_positions_from_the_packed_fetch(self):
        """ROADMAP 1a: after a stacked text round, every doc's RGA
        positions came out of the ONE packed (D, cap) fetch — diff
        emission pays zero per-object linearize dispatches."""
        lane = ShardLane(0)
        lane.ingest({f"t{i}": [text_change("a", 1, f"hello-{i}",
                                           obj=f"t{i}")]
                     for i in range(4)})
        s = stacked.LAST_STATS
        assert s["text_docs"] == 4
        assert s["pos_seeded"] == s["text_finalized"] == 4
        for i in range(4):
            doc = lane.docs[f"t{i}"]
            assert doc._pos_cache is not None
            assert len(doc._pos_cache) == doc.n_elems + 1
            assert doc.text() == f"hello-{i}"

    def test_single_doc_round_falls_back_per_object(self):
        lane = ShardLane(0)
        lane.ingest({"solo": [text_change("a", 1, "only", obj="solo")]})
        assert lane.stats["per_object_applies"] == 1
        assert lane.stats["stacked_applies"] == 0
        assert lane.docs["solo"].text() == "only"

    def test_hottest_doc_tracks_lifetime_ops(self):
        lane = ShardLane(0, doc_kind="map")
        lane.ingest({"cold": [map_change("a", 1, "cold", [("k", 1)])],
                     "hot": [map_change("a", 1, "hot",
                                        [(f"k{j}", j)
                                         for j in range(8)])]})
        doc_id, ops = lane.hottest_doc()
        assert doc_id == "hot" and ops == 8


# ---------------------------------------------------------------------------
# shard-count invariance: the tier's headline contract
# ---------------------------------------------------------------------------


def chaotic_stream(seed, n_docs=6, n_actors=2, n_seqs=3, hot_doc=None,
                   hot_factor=3, n_chunks=5):
    """Per-doc causally-chained multi-actor histories, fully shuffled
    across docs and seqs (premature arrivals guaranteed) with ~10%
    duplicated deliveries, chunked into serving rounds. Same seed →
    byte-identical schedule, whatever the shard count."""
    rng = np.random.default_rng(seed * 7919 + 17)
    docs = [f"inv-{seed}-{i}" for i in range(n_docs)]
    flat = []
    for di, doc in enumerate(docs):
        seqs = n_seqs * (hot_factor if doc == hot_doc else 1)
        for s in range(1, seqs + 1):
            for a in range(n_actors):
                actor = f"w{a}"
                base = (s - 1) * 2 + 1
                after = None if s == 1 else f"{actor}:{base - 1}"
                deps = {} if s == 1 else \
                    {f"w{b}": s - 1 for b in range(n_actors) if b != a}
                flat.append((doc, text_change(
                    actor, s, chr(97 + (s + a + di) % 26) * 2,
                    start_ctr=base, after=after, deps=deps, obj=doc)))
    rng.shuffle(flat)
    for i in rng.choice(len(flat), max(1, len(flat) // 10),
                        replace=False):
        flat.insert(int(rng.integers(0, len(flat))), flat[int(i)])
    per = max(1, -(-len(flat) // n_chunks))
    rounds = []
    for c in range(0, len(flat), per):
        chunk = {}
        for doc, ch in flat[c: c + per]:
            chunk.setdefault(doc, []).append(ch)
        rounds.append(chunk)
    return docs, rounds


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_shard_count_invariance(seed):
    """1-, 2-, and 8-shard runs of the same seeded chaotic session
    converge to byte-identical checkpoint-bundle bytes (tables, clocks,
    dep closures — the change history) and rendered texts."""
    results = {}
    for n_shards in (1, 2, 8):
        docs, rounds = chaotic_stream(seed)
        mesh = ShardedDocSet(n_shards=n_shards, capacity=64)
        for chunk in rounds:
            mesh.deliver_round(chunk)
        for doc in docs:
            assert mesh.quarantined(doc) == 0, \
                f"quarantine not drained for {doc} at {n_shards} shards"
        results[n_shards] = ({d: mesh.capture(d) for d in docs},
                             mesh.texts())
    bundles1, texts1 = results[1]
    for n_shards in (2, 8):
        bundles, texts = results[n_shards]
        assert texts == texts1, f"texts diverged at {n_shards} shards"
        for doc in bundles1:
            assert bundles[doc] == bundles1[doc], \
                f"bundle bytes of {doc} diverged at {n_shards} shards"


def test_invariance_with_forced_migration_mid_stream(seed=7):
    """The acceptance form: an 8-shard run that MIGRATES a doc between
    serving rounds still lands byte-identical with the 1-shard run."""
    docs, rounds = chaotic_stream(seed, n_chunks=4)
    ref = ShardedDocSet(n_shards=1, capacity=64)
    for chunk in rounds:
        ref.deliver_round(chunk)
    mesh = ShardedDocSet(n_shards=8, capacity=64)
    moved = 0
    for i, chunk in enumerate(rounds):
        mesh.deliver_round(chunk)
        victim = docs[i % len(docs)]
        if mesh.doc(victim) is not None:
            dst = (mesh.placement.shard_of(victim) + 3) % 8
            moved += mesh.migrate(victim, dst)
    assert moved >= 2, "migrations never engaged"
    assert mesh.texts() == ref.texts()
    for doc in docs:
        assert mesh.quarantined(doc) == 0
        assert mesh.capture(doc) == ref.capture(doc)


# ---------------------------------------------------------------------------
# migration: the quarantine handshake
# ---------------------------------------------------------------------------


class TestMigration:
    def test_migration_under_premature_quarantine(self):
        """The regression the ISSUE names: a doc migrates while
        causally-premature changes for it sit in the router quarantine;
        the parked changes survive the move and apply on the NEW owner
        once their deps arrive."""
        mesh = ShardedDocSet(n_shards=4, capacity=64)
        doc = "handshake"
        ch1 = text_change("w0", 1, "ab", obj=doc)
        ch2 = text_change("w0", 2, "cd", start_ctr=3, after="w0:2",
                          obj=doc)
        mesh.deliver(doc, [ch1])
        # seq 3 depends on seq 2 the mesh has never seen → parks
        ch3 = text_change("w0", 3, "ef", start_ctr=5, after="w0:4",
                          obj=doc)
        mesh.deliver(doc, [ch3])
        assert mesh.quarantined(doc) == 1
        src = mesh.placement.shard_of(doc)
        dst = (src + 1) % 4
        assert mesh.migrate(doc, dst)
        assert mesh.placement.shard_of(doc) == dst
        assert mesh.lanes[src].docs.get(doc) is None
        assert mesh.quarantined(doc) == 1      # still parked, still owned
        mesh.deliver(doc, [ch2])               # the missing link
        assert mesh.quarantined(doc) == 0
        assert mesh.texts()[doc] == "abcdef"
        assert mesh.stats["migrations"] == 1

    def test_deliveries_during_the_move_pen_and_replay(self):
        """While the doc has NO owner (mid-export/adopt), arriving
        deliveries pen; after the move they replay through the normal
        gate — ready ones apply on the new owner, premature ones go to
        quarantine."""
        mesh = ShardedDocSet(n_shards=2, capacity=64)
        doc = "pen"
        mesh.deliver(doc, [text_change("w0", 1, "xy", obj=doc)])
        ready = text_change("w0", 2, "zz", start_ctr=3, after="w0:2",
                            obj=doc)
        premature = text_change("w0", 4, "!!", start_ctr=7,
                                after="w0:6", obj=doc)

        def mid_move():
            mesh.deliver_round({doc: [ready]})
            mesh.deliver_round({doc: [premature]})

        src = mesh.placement.shard_of(doc)
        assert mesh.migrate(doc, 1 - src, _mid_migration=mid_move)
        assert mesh.stats["migration_parked"] == 2
        assert mesh.texts()[doc] == "xyzz"     # ready replayed + applied
        assert mesh.quarantined(doc) == 1      # premature re-parked
        mesh.deliver(doc, [text_change("w0", 3, "..", start_ctr=5,
                                       after="w0:4", obj=doc)])
        assert mesh.quarantined(doc) == 0
        assert mesh.texts()[doc] == "xyzz..!!"

    def test_migrate_defers_on_causally_unready_engine_queue(self):
        """A doc whose ENGINE still queues causally-unready work (fed
        around the router) refuses to move — migration defers rather
        than strand a causal hole in the bundle."""
        mesh = ShardedDocSet(n_shards=2, capacity=64)
        doc = "defer"
        lane = mesh.lane_of(doc)
        engine = lane.ensure_doc(doc)
        engine.apply_changes([text_change("w0", 2, "late", start_ctr=9,
                                          after="w0:8", obj=doc)])
        assert engine.queue                     # engine parked it
        src = mesh.placement.shard_of(doc)
        assert mesh.migrate(doc, 1 - src) is False
        assert mesh.stats["migrations_deferred"] == 1
        assert mesh.placement.shard_of(doc) == src

    def test_unmaterialized_doc_moves_as_a_table_entry(self):
        mesh = ShardedDocSet(n_shards=4, capacity=64)
        assert mesh.migrate("never-seen", 2)
        assert mesh.placement.shard_of("never-seen") == 2
        assert mesh.stats["migrations"] == 0    # no bundle moved

    def test_failed_adopt_restores_the_source_and_replays_the_pen(self):
        """Failure atomicity: if the destination adopt raises, the doc
        is restored on the SOURCE lane from the bundle in hand,
        placement never moves, and penned deliveries still replay —
        nothing is lost, nothing half-applies."""
        mesh = ShardedDocSet(n_shards=2, capacity=64)
        doc = "atomic"
        mesh.deliver(doc, [text_change("w0", 1, "ab", obj=doc)])
        src = mesh.placement.shard_of(doc)
        dst = 1 - src
        penned = text_change("w0", 2, "cd", start_ctr=3, after="w0:2",
                             obj=doc)

        def exploding_adopt(doc_id, bundle):
            mesh.deliver_round({doc: [penned]})     # pens mid-move
            raise RuntimeError("destination device lost")

        mesh.lanes[dst].adopt = exploding_adopt
        with pytest.raises(RuntimeError):
            mesh.migrate(doc, dst)
        assert mesh.placement.shard_of(doc) == src   # never moved
        assert mesh.lanes[src].docs.get(doc) is not None
        assert mesh.stats["migrations"] == 0
        assert mesh.texts()[doc] == "abcd"           # pen replayed home
        assert mesh.quarantined(doc) == 0

    def test_migrate_to_home_shard_is_a_noop(self):
        mesh = ShardedDocSet(n_shards=4, capacity=64)
        doc = "homer"
        mesh.deliver(doc, [text_change("w0", 1, "hi", obj=doc)])
        assert mesh.migrate(doc, mesh.placement.shard_of(doc)) is False


# ---------------------------------------------------------------------------
# the rebalance policy
# ---------------------------------------------------------------------------


class TestRebalancer:
    def _hot_pair(self, n_shards=4):
        """(mesh, hot_doc, co_tenant): two docs sharing a lane so the
        policy has a real co-tenant to relieve."""
        mesh = ShardedDocSet(n_shards=n_shards, doc_kind="map",
                             capacity=64)
        by_shard = {}
        i = 0
        while True:
            doc = f"reb-{i}"
            shard = mesh.placement.shard_of(doc)
            if shard in by_shard:
                return mesh, doc, by_shard[shard]
            by_shard[shard] = doc
            i += 1

    def test_telemetry_triggered_hot_doc_migration(self):
        mesh, hot, co = self._hot_pair()
        reb = mesh.attach_rebalancer(ratio=2.0, min_ops=32, cooldown=2)
        mesh.deliver_round({co: [map_change("a", 1, co, [("k", 0)])]})
        home = mesh.placement.shard_of(hot)
        for s in range(1, 12):
            mesh.deliver_round({hot: [map_change(
                "a", s, hot, [(f"k{j}", s) for j in range(16)])]})
            if reb.stats["migrations"]:
                break
        assert reb.stats["migrations"] == 1, \
            (reb.stats, reb.window_loads())
        assert mesh.placement.shard_of(hot) != home
        assert mesh.placement.table(), "no explicit placement entry"
        # telemetry counter mirrors the move
        assert mesh.stats["migrations"] == 1
        # cooldown holds the next decision back
        assert reb._cooling > 0

    def test_idle_mesh_never_migrates_on_noise(self):
        mesh, hot, co = self._hot_pair()
        reb = mesh.attach_rebalancer(ratio=2.0, min_ops=10_000,
                                     cooldown=0)
        for s in range(1, 6):
            mesh.deliver_round({hot: [map_change("a", s, hot,
                                                 [("k", s)])]})
        assert reb.stats["migrations"] == 0    # min_ops floor holds

    def test_single_resident_doc_is_never_relabeled(self):
        """Moving a lane's only doc just relabels the imbalance."""
        mesh = ShardedDocSet(n_shards=2, doc_kind="map", capacity=64)
        reb = mesh.attach_rebalancer(ratio=1.5, min_ops=8, cooldown=0)
        doc = "lonely"
        for s in range(1, 8):
            mesh.deliver_round({doc: [map_change(
                "a", s, doc, [(f"k{j}", s) for j in range(8)])]})
        assert reb.stats["migrations"] == 0


# ---------------------------------------------------------------------------
# the zero-collective invariant, from compiled HLO
# ---------------------------------------------------------------------------


def test_commit_path_compiles_with_zero_collectives():
    """The stacked round kernels, lowered with every operand sharded
    over the doc-axis mesh (the suite runs on 8 virtual cpu devices),
    contain no all-reduce / all-gather / all-to-all / collective-permute
    / reduce-scatter: scale-out moves ZERO bytes between devices."""
    import jax
    from automerge_tpu.shard.audit import (assert_zero_collectives,
                                           commit_path_collectives)
    if len(jax.devices()) < 2:
        pytest.skip("single-device backend: doc mesh is trivial")
    audit = commit_path_collectives()
    assert set(audit) == {"stacked_map_round", "stacked_mixed_round",
                          "stacked_scatter_registers",
                          "fused_stacked_round",
                          "fused_scatter_registers",
                          # ISSUE 18: the ring-commit megakernels ride
                          # the same audit (the PR-17 leftover)
                          "merge_and_materialize_dense_planned",
                          "merge_and_materialize_dense",
                          # ISSUE 19: their fused-tier twins
                          "fused_commit_round",
                          "fused_commit_round_planned"}
    assert_zero_collectives(audit)


def test_audit_counts_a_real_collective():
    """The auditor is not a rubber stamp: a program that genuinely
    all-reduces over the doc axis is reported."""
    import jax
    import jax.numpy as jnp
    from automerge_tpu.shard.audit import (assert_zero_collectives,
                                           count_collectives, doc_mesh)
    if len(jax.devices()) < 2:
        pytest.skip("single-device backend: doc mesh is trivial")
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = doc_mesh()
    shard = NamedSharding(mesh, P("doc"))
    x = jax.device_put(
        np.ones((mesh.shape["doc"] * 2, 8), np.float32), shard)
    fn = jax.jit(lambda a: jnp.sum(a),          # cross-doc reduction
                 in_shardings=(shard,), out_shardings=None)
    counts = count_collectives(fn, (x,))
    assert counts, "all-reduce over the doc axis went unreported"
    with pytest.raises(AssertionError):
        assert_zero_collectives({"bad_kernel": counts})


# ---------------------------------------------------------------------------
# DocSet unification (ROADMAP 1b): graduated group rides stacked
# ---------------------------------------------------------------------------


class TestDocSetStackedUnification:
    def _graduating_batches(self, ids, seq, text="abc"):
        from automerge_tpu.engine import TextChangeBatch
        out = {}
        for i, obj in enumerate(ids):
            # a delete makes the batch irregular → the fast tier
            # graduates the doc to its own engine
            chs = [text_change("w", seq, text, obj=obj,
                               start_ctr=seq * 10 + 1,
                               after=None if seq == 1
                               else f"w:{(seq - 1) * 10 + len(text)}")]
            if seq == 2:
                chs.append({"actor": "x", "seq": 1, "deps": {}, "ops": [
                    {"action": "del", "obj": obj,
                     "key": f"w:{10 + len(text)}"}]})
            out[obj] = TextChangeBatch.from_changes(chs, obj)
        return out

    def test_graduated_group_parity_across_routes(self, monkeypatch):
        """The stacked route (default) and the pre-unification per-doc
        loop (AMTPU_DOCSET_STACKED=0, the one-release comparator)
        commit byte-identical graduated engine state and texts."""
        from automerge_tpu.checkpoint import capture_engine
        from automerge_tpu.engine import DeviceTextDocSet
        ids = [f"uni{i}" for i in range(4)]
        results = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("AMTPU_DOCSET_STACKED", flag)
            ds = DeviceTextDocSet(ids)
            for seq in (1, 2, 3):
                ds.apply_batches(self._graduating_batches(ids, seq))
            bundles = {o: capture_engine(ds._overlay[ds._idx[o]])
                       for o in ids if ds._idx[o] in ds._overlay}
            assert bundles, "no doc ever graduated — test shape broken"
            results[flag] = (ds.texts(), bundles)
        assert results["1"] == results["0"]

    def test_graduated_group_takes_one_stacked_apply(self, monkeypatch):
        from automerge_tpu.engine import DeviceTextDocSet
        monkeypatch.setenv("AMTPU_DOCSET_STACKED", "1")
        ids = [f"st{i}" for i in range(4)]
        ds = DeviceTextDocSet(ids)
        ds.apply_batches(self._graduating_batches(ids, 1))
        ds.apply_batches(self._graduating_batches(ids, 2))  # graduates
        s = dict(stacked.LAST_STATS)
        assert s and s["text_docs"] == 4, s
        assert s["pos_seeded"] == s["text_finalized"] == 4


# ---------------------------------------------------------------------------
# SyncService wiring: rooms map onto shard lanes
# ---------------------------------------------------------------------------


def test_service_rooms_map_onto_shard_lanes():
    from automerge_tpu.service import ServiceConfig, SyncService
    svc = SyncService(ServiceConfig(shard_lanes=2))
    for r in range(6):
        svc.room(f"room-{r}")
    smap = svc.shard_map()
    assert smap["n_lanes"] == 2
    placed = [r for lane in smap["lanes"].values() for r in lane["rooms"]]
    assert sorted(placed) == [f"room-{r}" for r in range(6)]
    # deterministic: same room id → same lane, always
    for lane_idx, lane in smap["lanes"].items():
        for room in lane["rooms"]:
            assert hash_shard(room, 2) == lane_idx
    assert svc.metrics()["shard_lanes"] == 2
    assert "shards" in svc.describe()


def test_service_unsharded_default_is_unchanged():
    from automerge_tpu.service import ServiceConfig, SyncService
    svc = SyncService(ServiceConfig())
    svc.room("r")
    assert svc.shard_map() == {}
    assert svc.metrics()["shard_lanes"] == 0
    assert "shards" not in svc.describe()

"""Compiled-HLO collective audit of the sharded commit path.

The serving tier's scaling claim rests on one invariant: the commit
path moves ZERO bytes between devices. The lanes make that structural
(every lane program is single-device), and this module proves the
stronger SPMD formulation the mesh design rests on (docs/SHARDING_r5.md):
the PR-7 stacked round kernels, lowered with every operand sharded over
a doc-only mesh, compile to modules containing **no all-reduce /
all-gather / all-to-all / collective-permute / reduce-scatter** — XLA's
partitioner agrees the doc axis is embarrassingly parallel for the real
round kernels, not just for the simplified `merge_step` the earlier
evidence audited. `bench.py --sharded` runs this audit and records the
counts in the cfg12 session row; tests assert the zero.
"""

from __future__ import annotations

import re

import numpy as np

COLLECTIVES = ("all-gather", "all-reduce", "all-to-all",
               "collective-permute", "reduce-scatter")


def count_collectives(lowerable, args) -> dict:
    """Compile and count collective ops in the HLO text (zero-count
    keys dropped — an empty dict IS the pass)."""
    hlo = lowerable.lower(*args).compile().as_text()
    counts = {c: len(re.findall(rf"\b{c}\b", hlo)) for c in COLLECTIVES}
    return {c: n for c, n in counts.items() if n}


def doc_mesh(n_devices: int = None):
    """A doc-axis-only mesh over the available devices."""
    import jax
    from jax.sharding import Mesh
    devices = jax.devices()
    if n_devices:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("doc",))


def commit_path_collectives(mesh=None, docs_per_device: int = 2,
                            cap: int = 256) -> dict:
    """Audit the three stacked commit-path kernels over a doc-sharded
    mesh: {kernel name: {collective: count}} (empty inner dicts = the
    zero-collective invariant holds). Shapes are small — the audit is
    about partitioning structure, not scale."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ops import ingest as K

    if mesh is None:
        mesh = doc_mesh()
    shard = NamedSharding(mesh, P("doc"))
    D = mesh.shape["doc"] * docs_per_device
    M, R, N, Kc, T, S = 64, 64, 256, 64, 64, 64

    def put(arr):
        return jax.device_put(arr, shard)

    i32 = np.int32
    elem_tables = (put(np.zeros((D, cap), i32)),          # parent
                   put(np.zeros((D, cap), i32)),          # ctr
                   put(np.zeros((D, cap), i32)),          # actor
                   put(np.zeros((D, cap), i32)),          # value
                   put(np.zeros((D, cap), bool)),         # has_value
                   put(np.full((D, cap), -1, i32)),       # win_actor
                   put(np.zeros((D, cap), i32)),          # win_seq
                   put(np.zeros((D, cap), bool)),         # win_counter
                   put(np.zeros((D, cap), bool)))         # chain
    reg_tables = (put(np.zeros((D, cap), i32)),           # value
                  put(np.zeros((D, cap), bool)),          # has_value
                  put(np.full((D, cap), -1, i32)),        # win_actor
                  put(np.zeros((D, cap), i32)),           # win_seq
                  put(np.zeros((D, cap), bool)))          # win_counter

    out = {}
    # one causal round of every map/table object on the mesh
    ops = np.zeros((D, 5, M), i32)
    ops[:, K.MOP_KIND, :] = -1
    ops[:, K.MOP_SLOT, :] = cap
    conflict = np.full((D, Kc), cap, i32)
    map_fn = jax.jit(
        lambda *a: K.stacked_map_round(*a, out_cap=cap),
        in_shardings=(shard,) * 7, out_shardings=shard)
    out["stacked_map_round"] = count_collectives(
        map_fn, reg_tables + (put(ops), put(conflict)))

    # one causal round of every text/list object, the full static shape
    # (dense expansion + residuals + touches — the worst case)
    desc = np.zeros((D, 9, R), i32)
    desc[:, K.DESC_ELEM_BASE, :] = N
    blob = np.zeros((D, N), i32)
    res = np.zeros((D, 8, M), i32)
    res[:, 0, :] = -1
    res[:, K.RES_SLOT, :] = cap
    res[:, K.RES_NEW_SLOT, :] = cap
    touch = np.zeros((D, 3, T), i32)
    touch[:, 1:, :] = -1
    mixed_fn = jax.jit(
        lambda *a: K.stacked_mixed_round(
            *a, out_cap=cap, expand_kind="dense", with_res=True,
            with_touch=True),
        in_shardings=(shard,) * 14, out_shardings=shard)
    out["stacked_mixed_round"] = count_collectives(
        mixed_fn, elem_tables + (put(desc), put(blob), put(res),
                                 put(conflict), put(touch)))

    # every object's host-resolved slow residue, one stacked scatter
    wb = np.zeros((D, 6, S), i32)
    wb[:, 0, :] = cap
    scatter_fn = jax.jit(
        lambda *a: K.stacked_scatter_registers(*a),
        in_shardings=(shard,) * 6, out_shardings=shard)
    out["stacked_scatter_registers"] = count_collectives(
        scatter_fn, reg_tables + (put(wb),))

    # ISSUE 17: the fused megakernel (both lanes in one program) and the
    # combined scatter must stay embarrassingly parallel over the doc
    # axis too. Audited on the "lax" scan rung — the audit is about the
    # SPMD partitioner's view of the doc axis, and the Pallas rung lowers
    # the same per-shard program bodies.
    from ..ops import fused_round as F
    fused_fn = jax.jit(
        lambda *a: F.fused_stacked_round(
            *a, map_cap=cap, text_cap=cap, with_map=True, with_text=True,
            mode="lax"),
        in_shardings=(shard,) * 21, out_shardings=shard)
    out["fused_stacked_round"] = count_collectives(
        fused_fn,
        reg_tables + (put(ops), put(conflict)) + elem_tables
        + (put(desc), put(blob), put(res), put(conflict), put(touch)))
    fscatter_fn = jax.jit(
        lambda *a: F.fused_scatter_registers(
            *a, with_map=True, with_text=True),
        in_shardings=(shard,) * 12, out_shardings=shard)
    out["fused_scatter_registers"] = count_collectives(
        fscatter_fn,
        reg_tables + (put(wb),) + elem_tables[3:8] + (put(wb),))

    # ISSUE 18 (the PR-17 leftover): the ring-commit megakernels — the
    # whole common-case merge round (dense expansion + materialization)
    # in ONE program, the pipelined ring's steady-state commit — must
    # also stay embarrassingly parallel over the doc axis. The raw
    # per-doc kernels vmap over the leading doc dimension.
    segplan = np.zeros((D, 4, S), i32)
    planned_fn = jax.jit(
        jax.vmap(lambda *a: K._merge_and_materialize_dense_planned(
            *a, out_cap=cap, S=S, as_u8=True, L=cap)),
        in_shardings=(shard,) * 12, out_shardings=shard)
    out["merge_and_materialize_dense_planned"] = count_collectives(
        planned_fn, elem_tables + (put(desc), put(blob), put(segplan)))
    dense_fn = jax.jit(
        jax.vmap(lambda *a: K._merge_and_materialize_dense(
            *a, out_cap=cap, S=S, as_u8=True, L=cap)),
        in_shardings=(shard,) * 11, out_shardings=shard)
    out["merge_and_materialize_dense"] = count_collectives(
        dense_fn, elem_tables + (put(desc), put(blob)))

    # ISSUE 19: the fused-tier ring-commit megakernels (the production
    # route of the pipelined commit path under AMTPU_FUSED_ROUNDS) must
    # hold the same invariant as their XLA comparators above. Audited on
    # the "lax" scan rung, same rationale as the fused stacked round.
    fused_planned_fn = jax.jit(
        jax.vmap(lambda *a: F._fused_commit_planned_core(
            *a, out_cap=cap, S=S, as_u8=True, L=cap, mode="lax")),
        in_shardings=(shard,) * 12, out_shardings=shard)
    out["fused_commit_round_planned"] = count_collectives(
        fused_planned_fn, elem_tables + (put(desc), put(blob),
                                         put(segplan)))
    fused_commit_fn = jax.jit(
        jax.vmap(lambda *a: F._fused_commit_core(
            *a, out_cap=cap, S=S, as_u8=True, L=cap, mode="lax")),
        in_shardings=(shard,) * 11, out_shardings=shard)
    out["fused_commit_round"] = count_collectives(
        fused_commit_fn, elem_tables + (put(desc), put(blob)))
    del jnp
    return out


def assert_zero_collectives(audit: dict):
    """The acceptance form: every audited commit-path kernel compiled
    with zero cross-device collectives."""
    bad = {k: v for k, v in audit.items() if v}
    assert not bad, (
        f"sharded commit path compiled with collectives: {bad} — the "
        "doc axis is no longer communication-free")

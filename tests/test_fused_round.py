"""ISSUE-17 fused-round kernels vs the XLA parity path (INTERNALS §21).

The fused tier (ops/fused_round.py, the AMTPU_FUSED_ROUNDS default) must
commit EXACTLY the XLA program path's state on every delivery — across
the full flag matrix (fused x AMTPU_STACKED_ROUNDS x AMTPU_COLUMNAR_PLAN),
randomized out-of-order/duplicated streams, and both the solo and stacked
executors — plus the TIGHTENED accounting contract: a fused stacked pass
is one megakernel dispatch + at most one combined scatter
(FUSED_PASS_DISPATCH_BUDGET), and the fused entry points recompile zero
times at steady state. The multi-channel Pallas scan that powers the
fused expansion is unit-tested here against numpy on both the interpret
and lax rungs.
"""

import random

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu.engine import stacked

from test_stacked_rounds import (canon, engine_state, make_board,
                                 rand_peer_changes)


@pytest.fixture(autouse=True)
def _small_gate(monkeypatch):
    """Engage the stacked path at test scale."""
    monkeypatch.setenv("AMTPU_STACKED_MIN_OPS", "1")


# ---------------------------------------------------------------------------
# multi_scan: the (K, N) multi-channel prefix sum (ops/scan_pallas.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(1, 5), (6, 513), (3, 1024), (6, 4096)])
def test_multi_scan_interpret_matches_numpy(shape):
    from automerge_tpu.ops.scan_pallas import multi_scan
    rng = np.random.default_rng(shape[0] * 10007 + shape[1])
    x = rng.integers(-5, 6, size=shape).astype(np.int32)
    got = np.asarray(multi_scan(x, interpret=True))
    assert np.array_equal(got, np.cumsum(x, axis=1))


def test_multi_scan_vmaps_under_interpret():
    """The megakernel runs multi_scan under jax.vmap over the doc axis;
    the batching rule must hold on the interpret rung cpu tier-1 uses."""
    import jax
    from automerge_tpu.ops.scan_pallas import multi_scan

    rng = np.random.default_rng(7)
    x = rng.integers(-3, 4, size=(4, 6, 700)).astype(np.int32)
    got = np.asarray(
        jax.jit(jax.vmap(lambda a: multi_scan(a, interpret=True)))(x))
    assert np.array_equal(got, np.cumsum(x, axis=2))


def test_cumsum_rows_lax_rung():
    from automerge_tpu.ops.fused_round import _cumsum_rows
    x = np.arange(12, dtype=np.int32).reshape(2, 6)
    assert np.array_equal(np.asarray(_cumsum_rows(x, "lax")),
                          np.cumsum(x, axis=1))


def test_fused_mode_ladder(monkeypatch):
    from automerge_tpu.ops import fused_round as F
    for rung in ("pallas", "interpret", "lax"):
        monkeypatch.setenv("AMTPU_FUSED_MODE", rung)
        assert F.fused_mode() == rung
    monkeypatch.delenv("AMTPU_FUSED_MODE")
    assert F.fused_mode() in ("pallas", "lax")   # backend-selected rung
    monkeypatch.setenv("AMTPU_FUSED_ROUNDS", "0")
    assert not F.fused_rounds_enabled()
    monkeypatch.delenv("AMTPU_FUSED_ROUNDS")
    assert F.fused_rounds_enabled()              # default ON


# ---------------------------------------------------------------------------
# direct kernel parity: the fused core vs the XLA comparator
# ---------------------------------------------------------------------------


def _synthetic_round(cap=64):
    """One mixed round's packed operands: a 3-element run, one residual
    insert + one contended set, the matching touch rows."""
    from automerge_tpu._common import KIND_INS, KIND_SET
    from automerge_tpu.ops import ingest as K

    R, N, M, T = 8, 8, 4, 4
    desc = np.zeros((9, R), np.int32)
    desc[K.DESC_ELEM_BASE] = N
    desc[K.DESC_HEAD_SLOT, 0] = 6
    desc[K.DESC_PARENT_SLOT, 0] = 2
    desc[K.DESC_CTR0, 0] = 10
    desc[K.DESC_ACTOR, 0] = 3
    desc[K.DESC_WIN_ACTOR, 0] = 1
    desc[K.DESC_WIN_SEQ, 0] = 4
    desc[K.DESC_ELEM_BASE, 0] = 0
    desc[K.DESC_HAS_VALUE, 0] = 1
    desc[K.DESC_META, K.META_N_ELEMS] = 3
    desc[K.DESC_META, K.META_BASE_SLOT] = 6
    desc[K.DESC_META, K.META_N_RUNS] = 1
    blob = np.zeros(N, np.int32)
    blob[:3] = [97, 98, 99]
    res = np.zeros((8, M), np.int32)
    res[K.RES_KIND] = -1
    res[K.RES_SLOT] = cap
    res[K.RES_NEW_SLOT] = cap
    res[K.RES_KIND, 0] = KIND_INS
    res[K.RES_SLOT, 0] = 3
    res[K.RES_NEW_SLOT, 0] = 9
    res[K.RES_CTR, 0] = 11
    res[K.RES_ACTOR, 0] = 4
    res[K.RES_KIND, 1] = KIND_SET
    res[K.RES_SLOT, 1] = 1
    res[K.RES_VALUE, 1] = 120
    res[K.RES_WIN_ACTOR, 1] = 2
    res[K.RES_WIN_SEQ, 1] = 7
    touch = np.zeros((3, T), np.int32)
    touch[1:] = -1
    touch[:, 0] = [2, 10, 3]
    touch[:, 1] = [3, 11, 4]
    conflict = np.full(4, cap, np.int32)
    return desc, blob, res, conflict, touch


def _fresh_tables(cap=64, n_elems=5):
    import jax.numpy as jnp

    parent = np.zeros(cap, np.int32)
    ctr = np.zeros(cap, np.int32)
    actor = np.zeros(cap, np.int32)
    value = np.zeros(cap, np.int32)
    has = np.zeros(cap, bool)
    wa = np.full(cap, -1, np.int32)
    ws = np.zeros(cap, np.int32)
    wc = np.zeros(cap, bool)
    chain = np.zeros(cap, bool)
    for s in range(1, n_elems + 1):
        parent[s] = s - 1
        ctr[s] = s
        actor[s] = 1
        value[s] = 64 + s
        has[s] = True
        wa[s] = 1
        ws[s] = s
        chain[s] = s > 1
    return tuple(jnp.asarray(a)
                 for a in (parent, ctr, actor, value, has, wa, ws, wc,
                           chain))


@pytest.mark.parametrize("mode", ["lax", "interpret"])
def test_fused_mixed_round_matches_apply_mixed_round(mode):
    from automerge_tpu.ops import fused_round as F
    from automerge_tpu.ops import ingest as K

    cap = 64
    desc, blob, res, conflict, touch = _synthetic_round(cap)
    xla = K.apply_mixed_round(
        *_fresh_tables(cap), desc, blob, res, conflict, touch,
        out_cap=cap, expand_kind="sparse", with_res=True, with_touch=True)
    fused = F.fused_mixed_round(
        *_fresh_tables(cap), desc, blob, res, conflict, touch,
        out_cap=cap, mode=mode)
    for a, b in zip(xla, fused):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_fused_dense_round_matches_live_prefix():
    """Dense rounds run the uniform scatter expansion in the fused core;
    the XLA dense path writes padded run-tail garbage past the live
    region, so parity is over the live prefix (the only slots any
    reader — save, to_json, later rounds — ever consumes)."""
    from automerge_tpu.ops import fused_round as F
    from automerge_tpu.ops import ingest as K

    cap = 64
    desc, blob, _res, _conflict, _touch = _synthetic_round(cap)
    dd, db, dr, dc, dt = F.round_dummies(cap)
    xla = K.apply_mixed_round(
        *_fresh_tables(cap), desc, blob, K._dummy_i32(), K._dummy_i32(),
        K._dummy_i32(), out_cap=cap, expand_kind="dense", with_res=False,
        with_touch=False)
    fused = F.fused_mixed_round(
        *_fresh_tables(cap), desc, blob, dr, dc, dt, out_cap=cap,
        mode="lax")
    live = 5 + 3 + 1                       # base elems + run + head slot
    for a, b in zip(xla[:9], fused[:9]):
        assert np.array_equal(np.asarray(a)[:live], np.asarray(b)[:live])


def test_megakernel_lanes_match_stacked_comparators():
    """Both lanes of one `fused_stacked_round` dispatch equal the
    per-lane XLA programs (`stacked_map_round` + the fused solo core)."""
    import jax.numpy as jnp
    from automerge_tpu._common import KIND_SET
    from automerge_tpu.ops import fused_round as F
    from automerge_tpu.ops import ingest as K

    cap, mcap, D, M = 64, 32, 3, 4
    desc, blob, res, conflict, touch = _synthetic_round(cap)
    per_doc = [_fresh_tables(cap) for _ in range(D)]
    stk = tuple(jnp.stack([per_doc[i][k] for i in range(D)])
                for k in range(9))
    bcast = lambda a: np.broadcast_to(a, (D,) + a.shape).copy()
    mv = jnp.zeros((D, mcap), jnp.int32)
    mh = jnp.zeros((D, mcap), bool)
    mwa = jnp.full((D, mcap), -1, jnp.int32)
    mws = jnp.zeros((D, mcap), jnp.int32)
    mwc = jnp.zeros((D, mcap), bool)
    ops = np.zeros((D, 5, M), np.int32)
    ops[:, K.MOP_KIND] = -1
    ops[:, K.MOP_SLOT] = mcap
    ops[0, K.MOP_KIND, 0] = KIND_SET
    ops[0, K.MOP_SLOT, 0] = 2
    ops[0, K.MOP_VALUE, 0] = 42
    ops[0, K.MOP_WIN_ACTOR, 0] = 1
    ops[0, K.MOP_WIN_SEQ, 0] = 1
    mconf = np.full((D, 4), mcap, np.int32)

    out = F.fused_stacked_round(
        mv, mh, mwa, mws, mwc, ops, mconf, *stk, bcast(desc), bcast(blob),
        bcast(res), bcast(conflict), bcast(touch), map_cap=mcap,
        text_cap=cap, with_map=True, with_text=True, mode="lax")
    assert len(out) == 16                  # 5+1 map, 9+1 text

    xla_map = K.stacked_map_round(mv, mh, mwa, mws, mwc, ops, mconf,
                                  out_cap=mcap)
    for a, b in zip(xla_map, out[:6]):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    solo = F.fused_mixed_round(*per_doc[0], desc, blob, res, conflict,
                               touch, out_cap=cap, mode="lax")
    for s, o in zip(solo, out[6:]):
        got = np.asarray(o)
        for d in range(D):
            assert np.array_equal(got[d], np.asarray(s))


# ---------------------------------------------------------------------------
# engine parity matrix: fused x AMTPU_STACKED_ROUNDS x AMTPU_COLUMNAR_PLAN
# ---------------------------------------------------------------------------


def _apply_flags(fused, stacked_flag, columnar, base, deliveries,
                 monkeypatch):
    monkeypatch.setenv("AMTPU_FUSED_ROUNDS", fused)
    monkeypatch.setenv("AMTPU_STACKED_ROUNDS", stacked_flag)
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", columnar)
    doc = base
    for chunk in deliveries:
        doc = am.apply_changes(doc, chunk)
    return doc


@pytest.mark.parametrize("stacked_flag", ["1", "0"])
@pytest.mark.parametrize("columnar", ["1", "0"])
@pytest.mark.parametrize("seed", [0, 1])
def test_fused_parity_matrix(seed, columnar, stacked_flag, monkeypatch):
    """Randomized out-of-order/duplicated chunked streams: the fused and
    XLA paths commit byte-identical saves + to_json + full engine state
    in every (stacked, columnar) flag cell."""
    rng = random.Random(seed)
    base = make_board()
    per_peer = rand_peer_changes(rng, base, n_actors=10, chained=True)
    changes = [c for cs in per_peer for c in cs]
    rng.shuffle(changes)                        # out-of-order delivery
    for _ in range(2):                          # duplicated deliveries
        changes.insert(rng.randrange(len(changes) + 1),
                       dict(rng.choice(changes)))
    chunks = []
    i = 0
    while i < len(changes):
        n = rng.randrange(1, 8)
        chunks.append(changes[i: i + n])
        i += n
    d1 = _apply_flags("1", stacked_flag, columnar, base, chunks,
                      monkeypatch)
    d0 = _apply_flags("0", stacked_flag, columnar, base, chunks,
                      monkeypatch)
    assert canon(d1) == canon(d0)
    assert am.save(d1) == am.save(d0)
    assert engine_state(d1) == engine_state(d0)


def test_fused_interpret_rung_engine_parity(monkeypatch):
    """The interpret rung (the real Pallas kernel under the interpreter)
    commits the same state as the lax rung end-to-end."""
    rng = random.Random(9)
    base = make_board()
    changes = [c for cs in rand_peer_changes(rng, base, n_actors=6)
               for c in cs]
    monkeypatch.setenv("AMTPU_FUSED_MODE", "interpret")
    d_i = _apply_flags("1", "1", "1", base, [changes], monkeypatch)
    monkeypatch.setenv("AMTPU_FUSED_MODE", "lax")
    d_l = _apply_flags("1", "1", "1", base, [changes], monkeypatch)
    assert canon(d_i) == canon(d_l)
    assert am.save(d_i) == am.save(d_l)
    assert engine_state(d_i) == engine_state(d_l)


# ---------------------------------------------------------------------------
# the tightened accounting contract
# ---------------------------------------------------------------------------


def _merge_stats(monkeypatch, fused, n_actors=12):
    monkeypatch.setenv("AMTPU_FUSED_ROUNDS", fused)
    monkeypatch.setenv("AMTPU_STACKED_ROUNDS", "1")
    rng = random.Random(5)
    base = make_board()
    changes = [c for cs in rand_peer_changes(rng, base,
                                             n_actors=n_actors)
               for c in cs]
    stacked.LAST_STATS.clear()
    am.apply_changes(base, changes)
    assert stacked.LAST_STATS, "stacked path did not engage"
    return dict(stacked.LAST_STATS)


def test_fused_budget_tightened(monkeypatch):
    """A fused stacked apply fits APPLY_DISPATCH_BASE +
    FUSED_PASS_DISPATCH_BUDGET per pass — 4, not the XLA path's 16 —
    and `assert_round_budget` enforces the tightened bound."""
    s = _merge_stats(monkeypatch, fused="1")
    assert s["fused"] is True
    stacked.assert_round_budget(s)
    assert s["dispatches"] <= (stacked.APPLY_DISPATCH_BASE
                               + stacked.FUSED_PASS_DISPATCH_BUDGET
                               * max(1, s["passes"]))
    assert (stacked.FUSED_PASS_DISPATCH_BUDGET
            < stacked.PASS_DISPATCH_BUDGET)


def test_fused_budget_asserts_not_bypassed(monkeypatch):
    """The tightened bound actually bites: a fused stats dict inflated
    past the fused ceiling fails the assert even though it would fit
    the legacy 16/pass budget."""
    s = _merge_stats(monkeypatch, fused="1")
    bad = dict(s)
    bad["dispatches"] = (stacked.APPLY_DISPATCH_BASE
                         + stacked.FUSED_PASS_DISPATCH_BUDGET
                         * max(1, s["passes"]) + 1)
    with pytest.raises(AssertionError):
        stacked.assert_round_budget(bad)
    stacked.assert_round_budget({**bad, "fused": False})  # legacy bound


def test_unfused_path_unchanged(monkeypatch):
    """AMTPU_FUSED_ROUNDS=0 runs the verbatim XLA program path: no fused
    label in the apply, legacy budget."""
    s = _merge_stats(monkeypatch, fused="0")
    assert s["fused"] is False
    stacked.assert_round_budget(s)


def test_fused_dispatch_count_object_independent(monkeypatch):
    """The megakernel collapse is object-count independent AND strictly
    cheaper per pass than the XLA path on the same workload."""
    s_small = _merge_stats(monkeypatch, fused="1", n_actors=6)
    s_big = _merge_stats(monkeypatch, fused="1", n_actors=18)
    per_pass_small = s_small["dispatches"] / max(1, s_small["passes"])
    per_pass_big = s_big["dispatches"] / max(1, s_big["passes"])
    assert per_pass_big <= per_pass_small + 1e-9
    s_xla = _merge_stats(monkeypatch, fused="0", n_actors=18)
    assert s_big["dispatches"] < s_xla["dispatches"]


def test_fused_steady_state_zero_recompiles(monkeypatch):
    """The fused entry points compile once per shape: re-applying an
    identically-shaped delivery recompiles NOTHING (the cfg17 in-run
    assert, pinned here at test scale)."""
    from automerge_tpu.obs import device_truth as dt

    monkeypatch.setenv("AMTPU_FUSED_ROUNDS", "1")
    monkeypatch.setenv("AMTPU_STACKED_ROUNDS", "1")

    def run():
        rng = random.Random(11)
        base = make_board()
        changes = [c for cs in rand_peer_changes(rng, base, n_actors=8)
                   for c in cs]
        am.apply_changes(base, changes)

    run()                                   # warmup compiles
    with dt.steady_state() as ss:
        run()                               # identical shapes
    fused_recompiles = {k: v for k, v in ss.recompiles.items()
                        if k[0].startswith("fused_")}
    assert fused_recompiles == {}


def test_doc_opt_out_pins_xla_path(monkeypatch):
    """A doc class with fused_rounds=False keeps the whole apply on the
    XLA comparator path even with the env gate on."""
    from automerge_tpu import frontend as Frontend

    monkeypatch.setenv("AMTPU_FUSED_ROUNDS", "1")
    monkeypatch.setenv("AMTPU_STACKED_ROUNDS", "1")
    rng = random.Random(3)
    base = make_board()
    changes = [c for cs in rand_peer_changes(rng, base, n_actors=6)
               for c in cs]
    core = Frontend.get_backend_state(base)._core
    docs = [core.root] + list(core.objects.values())
    try:
        for w in docs:
            w.doc.fused_rounds = False
        stacked.LAST_STATS.clear()
        am.apply_changes(base, changes)
        assert stacked.LAST_STATS.get("fused") is False
    finally:
        for w in docs:
            del w.doc.fused_rounds

#!/bin/bash
# Probe the TPU tunnel repeatedly for up to ~9.5 min; exit 0 the moment it's up.
# Writes status lines to /tmp/tpu_probe_status.txt
for i in $(seq 1 6); do
  echo "probe $i at $(date +%H:%M:%S)" >> /tmp/tpu_probe_status.txt
  if timeout 80 python -c "import jax; d=jax.devices(); assert d and d[0].platform=='tpu', d; print('TPU UP:', d)" >> /tmp/tpu_probe_status.txt 2>&1; then
    echo "TUNNEL_UP at $(date +%H:%M:%S)" >> /tmp/tpu_probe_status.txt
    exit 0
  fi
  sleep 10
done
echo "TUNNEL_DOWN after 6 probes at $(date +%H:%M:%S)" >> /tmp/tpu_probe_status.txt
exit 1

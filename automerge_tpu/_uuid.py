"""Injectable UUID factory — the determinism hook used throughout the tests.

Mirrors the reference's ``src/uuid.js`` (swappable factory, reset to default),
which the test-suite uses to pin nondeterminism (/root/reference/src/uuid.js:1-12).
"""

from __future__ import annotations

import uuid as _uuid_module


def _default_factory() -> str:
    return str(_uuid_module.uuid4())


_factory = _default_factory


def uuid() -> str:
    return _factory()


def set_factory(factory) -> None:
    global _factory
    _factory = factory


def reset() -> None:
    global _factory
    _factory = _default_factory

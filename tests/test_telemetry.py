"""Continuous telemetry tier (automerge_tpu/obs/telemetry.py, obs/prom.py,
service lag probes + describe/scrape — INTERNALS §14, ISSUE 9).

The contracts under test:

- **Emit-time exactness.** Telemetry-backed span/counter aggregates stay
  EXACT under forced trace-ring wraparound, while the retained-record
  view demonstrably diverges — the regression pin for the bug class
  this tier closes (`metrics_snapshot` histograms silently going
  inexact once the ring wrapped).
- **Bounded rolling windows.** The time-series ring holds at most
  `n_windows` windows; ancient windows roll off, totals don't.
- **Prometheus exposition.** `render`ed pages pass the format validator
  (TYPE-declared families, cumulative histogram buckets ending at +Inf
  and equal to `_count`); malformed pages are rejected; the stdlib
  scrape endpoint serves /metrics and /describe over real HTTP.
- **Replication-lag probes.** A tenant whose frames sit un-acked (or
  whose believed clock trails the room head) reports nonzero lag in
  ops and ticks; catching up returns it to zero; peaks are recorded.
- **Black-box postmortem.** `SyncService.describe()` JSON-round-trips
  with health-ladder states, budget/credit occupancy, the lag table,
  and the bounded degradation-event ring — with tracing OFF.
- **Nearest-rank percentiles** in `SyncService.metrics()`.
- **SLO gate** (benchmarks/slo_gate.py): regressions vs the committed
  session rows are detected; single-row groups seed, missing fields
  are reported.
"""

import json
import struct
import threading
import urllib.request
from collections import deque

import pytest

import automerge_tpu as am
from automerge_tpu import Text, obs
from automerge_tpu.obs import prom
from automerge_tpu.obs.recorder import span_totals
from automerge_tpu.obs.telemetry import (BUCKET_LOW, N_BUCKETS, Telemetry,
                                         bucket_index, bucket_le_ns)
from automerge_tpu.service import ServiceConfig, SyncService, TenantBudget
from automerge_tpu.sync import Connection, DocSet


@pytest.fixture(autouse=True)
def _tracing_off():
    obs.disable()
    obs.clear()
    yield
    obs.disable()


# ---------------------------------------------------------------------------
# the telemetry store
# ---------------------------------------------------------------------------


class TestTelemetryStore:
    def test_counters_and_span_aggregates_are_exact(self):
        tel = Telemetry()
        for i in range(100):
            tel.observe_count("svc", "shed", 2)
            tel.observe_span("svc", "tick", 1000 + i)
        assert tel.counters()[("svc", "shed")] == 200
        agg = tel.span_aggregates()[("svc", "tick")]
        assert agg["count"] == 100
        assert agg["total_ns"] == sum(1000 + i for i in range(100))
        assert agg["min_ns"] == 1000 and agg["max_ns"] == 1099

    def test_window_ring_is_bounded_and_rolls(self):
        tel = Telemetry(window_ns=100, n_windows=4)
        for w in range(32):        # 32 distinct windows through a 4-ring
            tel.observe_count("c", "n", 1, ts_ns=w * 100)
        wins = tel.windows()
        assert len(wins) <= 4                       # bounded
        assert [w["window"] for w in wins] == [28, 29, 30, 31]  # newest
        assert tel.counters()[("c", "n")] == 32     # totals never decay
        series = tel.series("c", "n")
        assert all(v == 1 for _, v in series)

    def test_stale_slots_roll_off_the_view(self):
        # a slot never reused keeps its old window in the ring — the
        # read side must drop anything more than one ring span behind
        # the newest, or series rates divide by a bogus horizon
        tel = Telemetry(window_ns=100, n_windows=4)
        tel.observe_count("c", "n", 1, ts_ns=100)       # wid 1, slot 1
        tel.observe_count("c", "n", 1, ts_ns=50_000)    # wid 500, slot 0
        assert [w["window"] for w in tel.windows()] == [500]
        assert tel.counters()[("c", "n")] == 2          # totals intact

    def test_stale_observation_never_clobbers_a_live_window(self):
        # an observation whose ts_ns is older than the whole ring (e.g.
        # a span longer than n_windows*window_ns landing with its START
        # timestamp) must be dropped from the window view — overwriting
        # the live slot would discard that window's accumulated deltas.
        # Exact aggregates still count it.
        tel = Telemetry(window_ns=100, n_windows=4)
        tel.observe_count("c", "n", 5, ts_ns=1050)      # wid 10, slot 2
        tel.observe_span("c", "s", 10, ts_ns=650)       # wid 6, slot 2
        wins = tel.windows()
        assert [w["window"] for w in wins] == [10]      # live slot kept
        assert wins[0]["counters"][("c", "n")] == 5     # delta intact
        assert tel.counters()[("c", "n")] == 5
        assert tel.span_view()[1][("c", "s")]["count"] == 1  # still exact

    def test_power_of_two_duration_lands_in_its_le_bucket(self):
        # inclusive-le semantics: 2^k ns belongs to the le=2^k bucket
        assert bucket_index(1 << BUCKET_LOW) == 0
        assert bucket_le_ns(bucket_index(2048)) == 2048.0
        tel = Telemetry()
        for _ in range(10):
            tel.observe_span("svc", "tick", 2048)
        assert tel.quantile_ns("svc", "tick", 0.99) == 2048.0

    def test_histogram_buckets_and_quantile_bound(self):
        tel = Telemetry()
        durs = [500, 2_000, 2_000, 1_000_000, 60_000_000_000]
        for d in durs:
            tel.observe_span("svc", "tick", d)
        hist = tel.histograms()[("svc", "tick")]
        assert sum(hist) == len(durs)
        assert hist[bucket_index(500)] >= 1
        assert hist[N_BUCKETS] == 1                 # 60 s -> overflow
        # conservative p50: upper edge of the bucket holding rank 3
        q50 = tel.quantile_ns("svc", "tick", 0.50)
        assert 2_000 <= q50 <= 4_096
        # p99 lands in the overflow bucket -> the exact tracked max
        assert tel.quantile_ns("svc", "tick", 0.99) == 60_000_000_000
        assert bucket_le_ns(N_BUCKETS) == float("inf")

    def test_gauges_last_value_wins_and_drop(self):
        tel = Telemetry()
        tel.set_gauge("lag", 5, tenant="a")
        tel.set_gauge("lag", 7, tenant="a")
        tel.set_gauge("lag", 1, tenant="b")
        g = tel.gauges()
        assert g[("lag", (("tenant", "a"),))] == 7
        tel.drop_gauge("lag", tenant="a")
        assert ("lag", (("tenant", "a"),)) not in tel.gauges()
        assert ("lag", (("tenant", "b"),)) in tel.gauges()

    def test_concurrent_writers_merge_exactly(self):
        tel = Telemetry()
        n_threads, n_each = 8, 500
        start = threading.Barrier(n_threads)

        def writer():
            start.wait()
            for _ in range(n_each):
                tel.observe_count("t", "x")
                tel.observe_span("t", "s", 100)

        threads = [threading.Thread(target=writer)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert tel.counters()[("t", "x")] == n_threads * n_each
        assert tel.span_aggregates()[("t", "s")]["count"] \
            == n_threads * n_each


# ---------------------------------------------------------------------------
# the ISSUE 9 regression pin: exact after wraparound
# ---------------------------------------------------------------------------


class TestWraparoundExactness:
    def test_span_totals_exact_while_ring_view_diverges(self):
        """Force trace-ring wraparound: the telemetry-backed spans in
        metrics_snapshot stay exact; the retained-record derivation
        (the pre-ISSUE-9 source) visibly loses history."""
        n = 1000
        with obs.tracing(capacity=32):      # tiny ring: 32/stripe
            obs.clear()
            for _ in range(n):
                t0 = obs.now()
                obs.span("plan", "prepare_batch", t0)
            snap = obs.metrics_snapshot()
            ring_view = span_totals(obs.snapshot())
        exact = snap["spans"]["plan.prepare_batch"]
        assert exact["count"] == n
        assert exact["total_ns"] >= exact["max_ns"] > 0
        # the old derivation is bounded by ring retention -> diverged
        assert ring_view[("plan", "prepare_batch")]["count"] < n
        assert snap["retained"] < snap["emitted"] == n

    def test_event_counters_flow_into_telemetry_windows(self):
        with obs.tracing(capacity=16):
            obs.clear()
            for _ in range(300):
                obs.event("chaos", "drop")
            tel = obs.telemetry()
            assert tel.counters()[("chaos", "drop")] == 300
            assert sum(v for _, v in tel.series("chaos", "drop")) == 300

    def test_since_ns_query_still_serves_ring_view(self):
        """A windowed metrics_snapshot(since_ns) query falls back to the
        retained records (documented): the telemetry store answers
        whole-session aggregates, the ring answers 'recently'."""
        with obs.tracing(capacity=64):
            obs.clear()
            t0 = obs.now()
            obs.span("plan", "prepare_batch", t0)
            cut = obs.now()
            t0 = obs.now()
            obs.span("plan", "prepare_batch", t0)
            snap = obs.metrics_snapshot(since_ns=cut)
        assert snap["spans"]["plan.prepare_batch"]["count"] == 1


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def _fed_telemetry():
    tel = Telemetry()
    for i in range(50):
        tel.observe_span("svc", "tick", 10_000 + i * 1000)
        tel.observe_count("svc", "shed", 1)
    tel.set_gauge("replication_lag_ops_max", 3)
    return tel


class TestPromExposition:
    def test_rendered_page_validates(self):
        page = prom.expose(prom.telemetry_families(_fed_telemetry()))
        counts = prom.validate_prom(page)
        assert counts["families"] >= 3 and counts["samples"] > 10
        assert "amtpu_events_total" in page
        assert 'le="+Inf"' in page and "_bucket" in page

    def test_histogram_buckets_cumulative_and_count_consistent(self):
        page = prom.expose(prom.telemetry_families(_fed_telemetry()))
        buckets = [line for line in page.splitlines()
                   if line.startswith("amtpu_span_seconds_bucket")]
        values = [float(line.rsplit(" ", 1)[1]) for line in buckets]
        assert values == sorted(values)             # cumulative
        count = [line for line in page.splitlines()
                 if line.startswith("amtpu_span_seconds_count")]
        assert float(count[0].rsplit(" ", 1)[1]) == values[-1] == 50

    def test_validator_rejects_malformed_pages(self):
        with pytest.raises(prom.PromValidationError):
            prom.validate_prom("")                  # empty
        with pytest.raises(prom.PromValidationError):
            prom.validate_prom("no_type_metric 1\n")   # undeclared
        bad_hist = ("# TYPE h histogram\n"
                    'h_bucket{le="0.1"} 5\n'
                    'h_bucket{le="+Inf"} 3\n')      # not cumulative
        with pytest.raises(prom.PromValidationError):
            prom.validate_prom(bad_hist)
        no_inf = ("# TYPE h histogram\n"
                  'h_bucket{le="0.1"} 5\n')
        with pytest.raises(prom.PromValidationError):
            prom.validate_prom(no_inf)

    def test_label_escaping_round_trips(self):
        tel = Telemetry()
        tel.set_gauge("g", 1, tenant='we"ird\nname')
        page = prom.expose(prom.telemetry_families(tel))
        prom.validate_prom(page)                    # must still parse

    def test_close_brace_in_label_value_round_trips(self):
        # Label values may legally contain '}' (callers control tenant
        # and room ids); the validator must not stop the label block at
        # the first brace it sees.
        tel = Telemetry()
        tel.set_gauge("g", 3, tenant="a}b", room="r}0")
        page = prom.expose(prom.telemetry_families(tel))
        counts = prom.validate_prom(page)
        assert counts["samples"] >= 1
        assert 'tenant="a}b"' in page

    def test_non_finite_values_render_and_validate(self):
        assert prom._fmt_value(float("nan")) == "NaN"
        assert prom._fmt_value(float("inf")) == "+Inf"
        assert prom._fmt_value(float("-inf")) == "-Inf"
        tel = Telemetry()
        tel.set_gauge("ratio", float("nan"))
        tel.set_gauge("floor", float("-inf"))
        page = prom.expose(prom.telemetry_families(tel))
        prom.validate_prom(page)
        assert "NaN" in page and "-Inf" in page

    def test_negative_exponent_values_validate(self):
        # Sub-1e-4 span totals render as e.g. '7.9763e-05'; the
        # validator must accept negative exponents.
        tel = Telemetry()
        tel.observe_span("svc", "tick", 79_763)     # _sum = 7.9763e-05 s
        page = prom.expose(prom.telemetry_families(tel))
        assert "e-05" in page
        prom.validate_prom(page)
        with pytest.raises(prom.PromValidationError):
            prom.validate_prom("# TYPE g gauge\ng 1e-\n")

    def test_span_view_consistent_under_concurrent_emit(self):
        # telemetry_families reads hist + aggregates via span_view(),
        # one lock pass per stripe: +Inf bucket == _count even while
        # writers keep emitting.
        tel = Telemetry()
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                tel.observe_span("svc", "tick", 50_000)

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(200):
                hists, aggs = tel.span_view()
                for key, buckets in hists.items():
                    assert sum(buckets) == aggs[key]["count"]
                page = prom.expose(prom.telemetry_families(tel))
                prom.validate_prom(page)
        finally:
            stop.set()
            for t in threads:
                t.join()


# ---------------------------------------------------------------------------
# service integration: lag probes, describe, scrape, percentiles
# ---------------------------------------------------------------------------


class _Client:
    """Deque-transport tenant (the run_all cfg11 shape): pump() flushes
    both directions; withholding pump_down() leaves server frames
    un-acked — the wire-lag scenario."""

    def __init__(self, svc, tid, room_id, base):
        self.svc, self.tid, self.room_id = svc, tid, room_id
        self.to_server, self.to_client = deque(), deque()
        self.ds = DocSet()
        self.ds.set_doc(room_id,
                        am.apply_changes(am.init(f"c-{tid}"), base))
        svc.connect(tid, room_id, self.to_client.append)
        from automerge_tpu.resilience import ResilientChannel
        self.chan = ResilientChannel(self.to_server.append, None)
        self.conn = Connection(self.ds, self.chan.send)
        self.chan._deliver = self.conn.receive_msg
        self.conn.open()

    def pump_up(self):
        while self.to_server:
            env = self.to_server.popleft()
            sess = self.svc.session(self.tid)
            if sess is not None:
                sess.on_wire(env)

    def pump_down(self):
        while self.to_client:
            self.chan.on_wire(self.to_client.popleft())
        self.chan.tick()

    def pump(self):
        self.pump_up()
        self.pump_down()

    def doc(self):
        return self.ds.get_doc(self.room_id)


def _seed(svc, room_id="r"):
    doc = am.change(am.init("origin"), lambda d: (
        d.__setitem__("t", Text("start")), d.__setitem__("m", {})))
    changes = am.get_all_changes(doc)
    svc.seed_doc(room_id, am.apply_changes(am.init("server"), changes))
    return changes


def _settle(svc, clients, max_ticks=300):
    for _ in range(max_ticks):
        for c in clients:
            c.pump()
        svc.tick()
        if svc.idle() and all(c.chan.idle and not c.to_server
                              and not c.to_client for c in clients):
            return
    raise AssertionError(f"never quiesced: {svc.metrics()}")


class TestReplicationLagProbes:
    def test_withheld_acks_report_wire_lag_then_recover(self):
        svc = SyncService()
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        b = _Client(svc, "b", "r", base)
        _settle(svc, [a, b])
        # a edits; b NEVER pumps its downlink -> the server's frames to
        # b sit un-acked in b's server-side channel
        a.ds.set_doc("r", am.change(
            a.doc(), lambda d: d["m"].__setitem__("k", 1)))
        for _ in range(4):
            a.pump()
            b.pump_up()            # acks nothing, receives nothing
            svc.tick()
        lag = svc.replication_lag()
        assert lag["b"]["ops"] >= 1, lag
        assert lag["b"]["wire_ops"] >= 1, lag
        first_ticks = lag["b"]["ticks"]
        assert first_ticks >= 1
        svc.tick()
        assert svc.replication_lag()["b"]["ticks"] > first_ticks
        assert lag["a"]["ops"] == 0                 # per-tenant, not global
        m = svc.metrics()
        assert m["max_lag_ops"] >= 1 and m["lagging_tenants"] == 1
        assert m["peak_lag_ops"] >= 1 and m["peak_lag_ticks"] >= 1
        # recovery: the withheld tenant drains -> lag returns to zero
        _settle(svc, [a, b])
        svc.probe_lag()
        lag = svc.replication_lag()
        assert lag["b"]["ops"] == 0 and lag["b"]["ticks"] == 0
        assert svc.metrics()["peak_lag_ops"] >= 1   # peaks are sticky

    def test_lag_counts_matrix_deficit_for_unsent_changes(self):
        """A tenant that revealed its clock but is owed changes the hub
        has not flushed yet (mid-tick view) shows the matrix component."""
        svc = SyncService()
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        _settle(svc, [a])
        room = svc.room("r")
        doc = room.doc_set.get_doc("r")
        with room.hub.batched():     # defer the flush: deficit visible
            room.doc_set.set_doc("r", am.change(
                doc, lambda d: d["m"].__setitem__("x", 1)))
            table = room.hub.replication_lag()
            assert table["a"]["ops"] >= 1
            assert table["a"]["docs"].get("r", 0) >= 1
        _settle(svc, [a])

    def test_probe_disabled_by_config(self):
        svc = SyncService(ServiceConfig(lag_probe_ticks=0))
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        for _ in range(3):
            a.pump()
            svc.tick()
        assert svc.stats["peak_lag_ops"] == 0       # never probed


class TestDescribeAndScrape:
    def test_describe_round_trips_with_tracing_off(self):
        assert not obs.ENABLED
        svc = SyncService(ServiceConfig(event_log=8))
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        _settle(svc, [a])
        svc.evict("a", reason="test")
        dump = json.loads(json.dumps(svc.describe(), default=str))
        assert dump["schema"] == "amtpu-postmortem-v1"
        assert dump["metrics"]["evictions"] == 1
        assert "a" not in dump["tenants"]           # evicted -> gone
        assert dump["rooms"]["r"]["quarantine"]["parked"] == 0
        kinds = [e["event"] for e in dump["events"]]
        assert "join" in kinds and "evict" in kinds
        # same key name as the soak summary / bench session row
        assert "tick_p99_ms_telemetry" in dump
        assert "lag" in dump and "config" in dump

    def test_describe_tenant_entry_carries_ladder_and_occupancy(self):
        svc = SyncService()
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        _settle(svc, [a])
        entry = svc.describe()["tenants"]["a"]
        for key in ("state", "starved_streak", "inbox", "inbox_cap",
                    "in_flight", "recv_buffered", "lag_ops", "lag_ticks",
                    "stats", "channel"):
            assert key in entry, key
        assert entry["state"] == "live"
        assert entry["inbox_cap"] == svc.config.default_budget.inbox_cap

    def test_event_ring_is_bounded(self):
        svc = SyncService(ServiceConfig(event_log=4))
        for i in range(10):
            svc._note("shed", msgs=i)
        assert len(svc.describe()["events"]) == 4
        assert svc.describe()["events"][-1]["msgs"] == 9

    def test_scrape_page_validates_and_carries_lag_series(self):
        svc = SyncService()
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        b = _Client(svc, "b", "r", base)
        _settle(svc, [a, b])
        a.ds.set_doc("r", am.change(
            a.doc(), lambda d: d["m"].__setitem__("k", 1)))
        for _ in range(3):
            a.pump()
            b.pump_up()
            svc.tick()
        page = svc.scrape()
        counts = prom.validate_prom(page)
        assert counts["families"] > 10
        assert "amtpu_svc_replication_lag_ops{" in page
        assert 'tenant="b"' in page
        assert "amtpu_svc_span_seconds_bucket" in page   # tick histogram
        _settle(svc, [a, b])

    def test_scrape_bounds_lag_series_to_config(self):
        svc = SyncService(ServiceConfig(prom_lag_series=2))
        base = _seed(svc)
        clients = [_Client(svc, f"t{i}", "r", base) for i in range(5)]
        _settle(svc, clients)
        page = svc.scrape()
        n = sum(1 for line in page.splitlines()
                if line.startswith("amtpu_svc_replication_lag_ops{"))
        assert n <= 2

    def test_http_endpoint_serves_metrics_and_describe(self):
        svc = SyncService()
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        _settle(svc, [a])
        srv = svc.serve_metrics()
        try:
            body = urllib.request.urlopen(
                srv.url + "/metrics", timeout=10).read().decode()
            prom.validate_prom(body)
            dump = json.loads(urllib.request.urlopen(
                srv.url + "/describe", timeout=10).read())
            assert dump["schema"] == "amtpu-postmortem-v1"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(srv.url + "/nope", timeout=10)
        finally:
            srv.close()

    def test_aborted_scrape_is_quiet(self, capfd):
        # a scraper that drops the connection mid-response must not dump a
        # socketserver traceback to stderr (the handler/handle_error guards)
        import socket
        svc = SyncService()
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        _settle(svc, [a])
        srv = svc.serve_metrics()
        try:
            for _ in range(5):
                s = socket.create_connection((srv.host, srv.port), timeout=5)
                s.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
                # abort hard (RST) without reading the body
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
                s.close()
            # a well-behaved scrape still works afterwards
            body = urllib.request.urlopen(
                srv.url + "/metrics", timeout=10).read().decode()
            prom.validate_prom(body)
        finally:
            srv.close()
        err = capfd.readouterr().err
        assert "Traceback" not in err, err

    def test_obs_telemetry_rides_along_when_tracing(self):
        svc = SyncService()
        base = _seed(svc)
        a = _Client(svc, "a", "r", base)
        with obs.tracing():
            obs.clear()
            _settle(svc, [a])
            page = svc.scrape()
        prom.validate_prom(page)
        assert "amtpu_obs_" in page


class TestMetricsPercentiles:
    def test_nearest_rank_indexing(self):
        svc = SyncService()
        svc._tick_ms.extend(float(i + 1) for i in range(100))  # 1..100
        m = svc.metrics()
        # nearest-rank: p50 of 1..100 is the 50th value, p99 the 99th
        assert m["p50_tick_ms"] == 50.0
        assert m["p99_tick_ms"] == 99.0
        assert m["max_tick_ms"] == 100.0

    def test_single_sample_and_empty(self):
        svc = SyncService()
        assert svc.metrics()["p99_tick_ms"] == 0.0
        svc._tick_ms.append(7.0)
        m = svc.metrics()
        assert m["p50_tick_ms"] == m["p99_tick_ms"] == 7.0

    def test_tick_history_is_bounded(self):
        svc = SyncService(ServiceConfig(tick_ring=16))
        for i in range(100):
            svc._tick_ms.append(float(i))
        assert len(svc._tick_ms) == 16              # deque maxlen


class TestPublicIntrospection:
    def test_hub_peer_state_lifecycle(self):
        ds = DocSet()
        doc = am.change(am.init("o"), lambda d: d.__setitem__("m", {}))
        ds.set_doc("d", doc)
        from automerge_tpu.sync.hub import SyncHub
        hub = SyncHub(ds)
        hub.open()
        hub.add_peer("p", lambda msg: None)
        hub.note_clock("p", "d", {})
        st = hub.peer_state("p")
        assert st["present"] and st["matrix_slot"]
        assert st["revealed_docs"] == 1
        hub.remove_peer("p")
        st = hub.peer_state("p")
        assert not st["present"] and not st["matrix_slot"]
        assert st["revealed_docs"] == st["session_docs"] == 0

    def test_gate_quarantine_items_snapshot(self):
        ds = DocSet()
        from automerge_tpu.resilience.inbound import InboundGate
        gate = InboundGate(ds)
        premature = {"actor": "x", "seq": 5, "deps": {"ghost": 3},
                     "ops": [], "message": ""}
        gate.deliver("doc", [premature], validated=True, sender="tEn")
        items = gate.quarantine_items()
        assert ("doc", "x", 5, "tEn") in items
        assert gate.quarantine_items("doc") == items
        assert gate.quarantine_items("other") == []
        assert gate.evict_sender("tEn") == 1
        assert gate.quarantine_items() == []

    def test_reclaimed_uses_public_surface(self):
        """reclaimed() must agree with the public introspection it now
        reads — evict, then both report clean."""
        svc = SyncService()
        base = _seed(svc)
        _Client(svc, "a", "r", base)
        svc.tick()
        svc.evict("a", reason="test")
        assert svc.reclaimed("a")
        st = svc.room("r").hub.peer_state("a")
        assert not st["present"] and not st["matrix_slot"]
        assert all(s != "a" for *_, s
                   in svc.room("r").gate.quarantine_items())


# ---------------------------------------------------------------------------
# the SLO gate
# ---------------------------------------------------------------------------


def _row(metric, value, platform="cpu", **extra):
    return {"metric": metric, "platform": platform, "value": value,
            **extra}


class TestSloGate:
    def test_throughput_regression_detected(self):
        from benchmarks import slo_gate
        rows = [_row("e2e_pipeline_ops_per_sec", 5_000_000,
                     serial_profile={"prepare_s": 0.02, "commit_s": 0.01}),
                _row("e2e_pipeline_ops_per_sec", 3_000_000,
                     serial_profile={"prepare_s": 0.02, "commit_s": 0.01})]
        findings = slo_gate.check(rows)
        viol = [f for f in findings if f["status"] == "violation"]
        assert any(f["field"] == "value" for f in viol)
        ok = [f for f in findings if f["status"] == "ok"]
        assert any(f["field"] == "serial_profile.prepare_s" for f in ok)

    def test_span_term_regression_detected(self):
        from benchmarks import slo_gate
        rows = [_row("e2e_pipeline_ops_per_sec", 5_000_000,
                     serial_profile={"prepare_s": 0.02, "commit_s": 0.01}),
                _row("e2e_pipeline_ops_per_sec", 5_000_000,
                     serial_profile={"prepare_s": 0.2, "commit_s": 0.01})]
        viol = [f for f in slo_gate.check(rows)
                if f["status"] == "violation"]
        assert any(f["field"] == "serial_profile.prepare_s" for f in viol)

    def test_service_slos_and_derived_shed_rate(self):
        from benchmarks import slo_gate
        rows = [_row("cfg11_service_200_sessions", 300.0, p99_tick_ms=100,
                     shed_total=0, admitted_ops=2000, max_lag_ops=0,
                     max_lag_ticks=0),
                _row("cfg11_service_200_sessions", 290.0, p99_tick_ms=400,
                     shed_total=1500, admitted_ops=2000, max_lag_ops=3,
                     max_lag_ticks=2)]
        findings = slo_gate.check(rows)
        viol = {f["field"] for f in findings if f["status"] == "violation"}
        assert "p99_tick_ms" in viol                # 4x > 1.5x slack
        assert "shed_rate" in viol                  # 0 -> 0.75/op
        assert "max_lag_ops" in viol                # absolute: nonzero
        assert "value" not in viol                  # 290 >= 0.7 * 300

    def test_single_row_seeds_and_missing_field_reported(self):
        from benchmarks import slo_gate
        rows = [_row("cfg11_service_50_sessions", 300.0,
                     shed_total=0, admitted_ops=100, max_lag_ops=0,
                     max_lag_ticks=0)]        # no p99_tick_ms
        findings = slo_gate.check(rows)
        assert any(f["status"] == "missing"
                   and f["field"] == "p99_tick_ms" for f in findings)
        assert all(f["status"] != "violation" for f in findings)

    def test_platforms_never_cross_compare(self):
        from benchmarks import slo_gate
        rows = [_row("e2e_pipeline_ops_per_sec", 100_000_000,
                     platform="axon"),
                _row("e2e_pipeline_ops_per_sec", 4_000_000,
                     platform="cpu")]
        assert all(f["status"] != "violation"
                   for f in slo_gate.check(rows))

    def test_gate_main_warn_only_exits_zero(self, tmp_path):
        from benchmarks import slo_gate
        log = tmp_path / "sessions.jsonl"
        rows = [_row("e2e_pipeline_ops_per_sec", 5_000_000),
                _row("e2e_pipeline_ops_per_sec", 1_000_000)]
        log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        assert slo_gate.main(["--sessions", str(log)]) == 0
        assert slo_gate.main(["--sessions", str(log), "--strict"]) == 1

"""Native C++ wire codec vs the Python decoder: identical columnar batches.

The native tier is optional — tests skip when no toolchain is available —
but when it builds, every in-scope payload must decode bit-identically to
`TextChangeBatch.from_changes`, and out-of-scope payloads must fall back.
"""

import json

import numpy as np
import pytest

from automerge_tpu.engine import DeviceTextDoc, TextChangeBatch
from automerge_tpu import native


def typing_change(actor, seq, text, start=1, after="_head", deps=None,
                  obj="t", message=None):
    ops = []
    key = after
    for i, c in enumerate(text):
        ops += [{"action": "ins", "obj": obj, "key": key, "elem": start + i},
                {"action": "set", "obj": obj, "key": f"{actor}:{start+i}",
                 "value": c}]
        key = f"{actor}:{start+i}"
    ch = {"actor": actor, "seq": seq, "deps": deps or {}, "ops": ops}
    if message is not None:
        ch["message"] = message
    return ch


def assert_batches_equal(a: TextChangeBatch, b: TextChangeBatch):
    assert a.actors == b.actors
    assert a.actor_table == b.actor_table
    assert a.deps == b.deps
    assert a.messages == b.messages
    np.testing.assert_array_equal(a.seqs, b.seqs)
    for f in ("op_change", "op_kind", "op_target_actor", "op_target_ctr",
              "op_parent_actor", "op_parent_ctr", "op_value"):
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f), f)


needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native toolchain unavailable")


@needs_native
def test_parity_typing():
    changes = [typing_change("alice", 1, "hello world", message="hi\nthere"),
               typing_change("bob", 1, "né±漢🎉", start=1,
                             deps={"alice": 1}),
               {"actor": "bob", "seq": 2, "deps": {}, "ops": [
                   {"action": "del", "obj": "t", "key": "alice:2"},
                   {"action": "ins", "obj": "t", "key": "bob:1", "elem": 99},
                   {"action": "set", "obj": "t", "key": "bob:99",
                    "value": "é"}]}]
    payload = json.dumps(changes)
    fast = native.decode_text_changes(payload, "t")
    assert fast is not None
    slow = TextChangeBatch.from_changes(changes, "t")
    assert_batches_equal(fast, slow)


@needs_native
def test_engine_accepts_native_batch():
    changes = [typing_change("w", 1, "native!")]
    batch = TextChangeBatch.from_json(json.dumps(changes), "t")
    doc = DeviceTextDoc("t").apply_batch(batch)
    assert doc.text() == "native!"


@needs_native
def test_out_of_scope_falls_back():
    # rich (multi-char) value -> native returns None, from_json still works
    changes = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
        {"action": "set", "obj": "t", "key": "a:1", "value": "multi-char"}]}]
    assert native.decode_text_changes(json.dumps(changes), "t") is None
    batch = TextChangeBatch.from_json(json.dumps(changes), "t")
    assert batch.value_pool[0]["value"] == "multi-char"


@needs_native
def test_escapes_and_unicode():
    changes = [{"actor": "aé", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
        {"action": "set", "obj": "t", "key": "aé:1",
         "value": "🎉"}]}]  # surrogate-pair emoji
    payload = json.dumps(changes)
    fast = native.decode_text_changes(payload, "t")
    slow = TextChangeBatch.from_changes(json.loads(payload), "t")
    assert fast is not None
    assert_batches_equal(fast, slow)


@needs_native
def test_pretty_printed_payload():
    """Whitespace/indentation in the wire JSON must not break decoding."""
    changes = [typing_change("alice", 1, "hi"),
               typing_change("bob", 1, "yo", deps={"alice": 1})]
    pretty = json.dumps(changes, indent=2)
    fast = native.decode_text_changes(pretty, "t")
    slow = TextChangeBatch.from_changes(changes, "t")
    assert fast is not None
    assert_batches_equal(fast, slow)


@needs_native
def test_newline_actor_falls_back():
    changes = [{"actor": "a\nb", "seq": 1, "deps": {}, "ops": []}]
    assert native.decode_text_changes(json.dumps(changes), "t") is None
    assert TextChangeBatch.from_json(json.dumps(changes), "t").actors == ["a\nb"]


@needs_native
@pytest.mark.parametrize("seed", range(6))
def test_run_detection_parity(seed):
    """Native single-pass run detection == numpy vectorized detection on
    random op batches (pairs, bare inserts, dels, incs, pooled values)."""
    from automerge_tpu.engine.runs import _detect_runs_numpy
    from automerge_tpu.native import detect_runs_native

    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 400))
    kind = np.zeros(n, np.int8)
    ta = rng.integers(0, 4, n).astype(np.int32)
    tc = rng.integers(1, 50, n).astype(np.int32)
    pa = rng.integers(-1, 4, n).astype(np.int32)
    pc = rng.integers(0, 50, n).astype(np.int32)
    val = rng.integers(-3, 300, n).astype(np.int64)
    row = np.sort(rng.integers(0, 5, n)).astype(np.int32)
    # sprinkle plausible pair/chain structure among random noise
    i = 0
    while i < n - 1:
        choice = rng.random()
        if choice < 0.5:
            kind[i] = 0          # INS
            kind[i + 1] = 1      # SET
            ta[i + 1] = ta[i]
            tc[i + 1] = tc[i]
            row[i + 1] = row[i]
            if rng.random() < 0.7 and i >= 2 and kind[i - 2] == 0:
                ta[i] = ta[i - 2]
                tc[i] = tc[i - 2] + 1
                pa[i] = ta[i - 2]
                pc[i] = tc[i - 2]
                row[i] = row[i - 2]
                tc[i + 1] = tc[i]
                ta[i + 1] = ta[i]
                row[i + 1] = row[i]
            i += 2
        else:
            kind[i] = int(rng.integers(0, 4))
            i += 1
    base = int(rng.integers(0, 100))
    a = _detect_runs_numpy(kind, ta, tc, pa, pc, val, row, base)
    out = detect_runs_native(kind, ta, tc, pa, pc, val, row, base)
    assert out is not None
    (hpos, run_len, head_slot, rpos, res_new_slot, blob, n_ins,
     lt128, lt256) = out
    np.testing.assert_array_equal(hpos, a.hpos)
    np.testing.assert_array_equal(run_len, a.run_len)
    np.testing.assert_array_equal(head_slot, a.head_slot)
    np.testing.assert_array_equal(rpos, a.rpos)
    np.testing.assert_array_equal(res_new_slot, a.res_new_slot)
    np.testing.assert_array_equal(blob, a.blob)
    assert n_ins == a.n_ins
    assert lt128 == a.blob_lt_128 and lt256 == a.blob_lt_256


@needs_native
def test_decode_speed_sanity():
    """The native decoder should beat the Python loop comfortably."""
    import time
    changes = [typing_change(f"actor-{a}", 1, "x" * 500)
               for a in range(20)]
    payload = json.dumps(changes)
    t0 = time.perf_counter()
    fast = native.decode_text_changes(payload, "t")
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    slow = TextChangeBatch.from_changes(json.loads(payload), "t")
    t_python = time.perf_counter() - t0
    assert_batches_equal(fast, slow)
    assert t_native < t_python  # typically 20-100x


@needs_native
def test_ins_without_elem_falls_back():
    # the python decoder rejects this input (KeyError); the native path must
    # not silently accept it with a corrupt -1 counter
    changes = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head"}]}]
    assert native.decode_text_changes(json.dumps(changes), "t") is None


@needs_native
def test_out_of_int32_range_falls_back():
    # oversized elem / elemId counter / seq must defer to python, not truncate
    big = 2 ** 31
    payloads = [
        [{"actor": "a", "seq": 1, "deps": {}, "ops": [
            {"action": "ins", "obj": "t", "key": "_head", "elem": big}]}],
        [{"actor": "a", "seq": 1, "deps": {}, "ops": [
            {"action": "del", "obj": "t", "key": f"a:{big}"}]}],
        [{"actor": "a", "seq": big, "deps": {}, "ops": []}],
    ]
    for changes in payloads:
        assert native.decode_text_changes(json.dumps(changes), "t") is None


@needs_native
def test_malformed_elem_id_falls_back_without_crash():
    # column alignment: the per-change fixup loop walks every column even on
    # the unsupported path, so a bad elemId must not short-push columns
    for key in ("nocolon", "a:", "a:12x"):
        changes = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
            {"action": "del", "obj": "t", "key": key},
            {"action": "ins", "obj": "t", "key": "_head", "elem": 1}]}]
        assert native.decode_text_changes(json.dumps(changes), "t") is None
        changes = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
            {"action": "ins", "obj": "t", "key": key, "elem": 1},
            {"action": "set", "obj": "t", "key": "a:1", "value": "x"}]}]
        assert native.decode_text_changes(json.dumps(changes), "t") is None


@needs_native
def test_llong_wrapping_int_falls_back():
    # 2**64+1 wraps long long accumulation without a guard; must fall back
    huge = str(2 ** 64 + 1)
    for payload in (
        '[{"actor": "a", "seq": 1, "deps": {}, "ops": [{"action": "ins", "obj": "t", "key": "_head", "elem": %s}]}]' % huge,
        '[{"actor": "a", "seq": %s, "deps": {}, "ops": []}]' % huge,
    ):
        assert native.decode_text_changes(payload, "t") is None


@needs_native
@pytest.mark.parametrize("seed", range(3))
def test_run_detection_parity_parallel_path(seed, monkeypatch):
    """Parity at sizes that cross the native detector's thread fan-out
    threshold (MIN_CHUNK = 2^19 ops per chunk), with long runs spanning
    chunk boundaries, boundary residuals, and pairs straddling the cut —
    the speculative-chunk stitch must be byte-identical to numpy.
    AMTPU_DETECT_THREADS forces the fan-out so the stitch actually runs
    even on single-core machines (where hardware_concurrency()==1 would
    silently take the serial branch)."""
    from automerge_tpu.engine.runs import _detect_runs_numpy
    from automerge_tpu.native import detect_runs_native

    monkeypatch.setenv("AMTPU_DETECT_THREADS", "3")
    rng = np.random.default_rng(900 + seed)
    n = 1_400_000 + int(rng.integers(0, 7))   # > 2 chunks, odd tails
    kind = np.full(n, 1, np.int8)
    ta = np.zeros(n, np.int32)
    tc = np.zeros(n, np.int32)
    pa = np.zeros(n, np.int32)
    pc = np.zeros(n, np.int32)
    val = np.zeros(n, np.int64)
    row = np.zeros(n, np.int32)
    i, r, c = 0, 0, 1
    while i < n - 1:
        pick = rng.random()
        if pick < 0.82:
            # a typing run of random length (often crossing a boundary)
            L = int(rng.integers(1, 120_000))
            L = min(L, (n - 1 - i) // 2)
            if L <= 0:
                break
            idx = i + 2 * np.arange(L)
            kind[idx] = 0
            kind[idx + 1] = 1
            a_ = int(rng.integers(0, 5))
            ta[idx] = a_
            ta[idx + 1] = a_
            ctr = c + np.arange(L)
            tc[idx] = ctr
            tc[idx + 1] = ctr
            pa[idx] = a_
            pc[idx] = ctr - 1
            pa[i] = int(rng.integers(0, 5))      # run head: foreign parent
            pc[i] = int(rng.integers(0, 50))
            val[idx + 1] = rng.integers(32, 300, L)
            row[idx] = r
            row[idx + 1] = r
            c += L + 1
            i += 2 * L
        else:
            # residual op (del/inc/bare ins) right at arbitrary offsets
            kind[i] = int(rng.integers(0, 4))
            ta[i] = int(rng.integers(0, 5))
            tc[i] = c
            c += 1
            i += 1
        r += 1
    a = _detect_runs_numpy(kind, ta, tc, pa, pc, val, row, 37)
    out = detect_runs_native(kind, ta, tc, pa, pc, val, row, 37)
    assert out is not None
    (hpos, run_len, head_slot, rpos, res_new_slot, blob, n_ins,
     lt128, lt256) = out
    np.testing.assert_array_equal(hpos, a.hpos)
    np.testing.assert_array_equal(run_len, a.run_len)
    np.testing.assert_array_equal(head_slot, a.head_slot)
    np.testing.assert_array_equal(rpos, a.rpos)
    np.testing.assert_array_equal(res_new_slot, a.res_new_slot)
    np.testing.assert_array_equal(blob, a.blob)
    assert n_ins == a.n_ins
    assert lt128 == a.blob_lt_128 and lt256 == a.blob_lt_256


@needs_native
def test_bulk_from_changes_routes_native_with_identical_batch():
    """from_changes itself re-serializes BULK dict payloads through the
    native decoder (columnar.py _NATIVE_MIN_OPS); the result must equal
    the pure-Python walk bit for bit."""
    text = "x" * (TextChangeBatch._NATIVE_MIN_OPS // 2 + 10)
    changes = [typing_change("alice", 1, text, message="bulk")]
    routed = TextChangeBatch.from_changes(changes, "t")
    # force the Python walk by staying under the ops floor per call:
    # decode the same payload with the native path disabled
    import automerge_tpu.engine.columnar as C
    orig = TextChangeBatch._NATIVE_MIN_OPS
    try:
        TextChangeBatch._NATIVE_MIN_OPS = 10**9
        slow = TextChangeBatch.from_changes(changes, "t")
    finally:
        TextChangeBatch._NATIVE_MIN_OPS = orig
    assert_batches_equal(routed, slow)


def test_bulk_malformed_change_still_raises():
    """The bulk native route must not LAUNDER malformed wire shapes the
    Python walk rejects: a change missing "seq" (or with a non-string
    message) takes the Python path and fails loudly."""
    text = "x" * (TextChangeBatch._NATIVE_MIN_OPS // 2 + 10)
    good = typing_change("alice", 1, text)
    bad = {k: v for k, v in good.items() if k != "seq"}
    with pytest.raises(KeyError):
        TextChangeBatch.from_changes([bad], "t")


@needs_native
def test_change_level_malformation_marked_unsupported_by_codec():
    """The codec itself (not a caller-side pre-scan) must decline changes
    the Python walk treats differently: missing actor/seq/ops, or a
    non-string message (which the Python path PRESERVES)."""
    import json as _json
    from automerge_tpu import native

    good = typing_change("alice", 1, "hi")
    for strip in ("actor", "seq", "ops"):
        bad = {k: v for k, v in good.items() if k != strip}
        assert native.decode_text_changes(
            _json.dumps([bad]), "t") is None, f"missing {strip} accepted"
    num_msg = dict(good, message=42)
    assert native.decode_text_changes(_json.dumps([num_msg]), "t") is None
    # null message means absent and stays in scope
    null_msg = dict(good, message=None)
    batch = native.decode_text_changes(_json.dumps([null_msg]), "t")
    assert batch is not None and batch.messages == [None]
